"""Fault injection + end-to-end failure recovery (ISSUE 9, tier-1).

The contracts pinned here, in dependency order:

- **Plan determinism**: a seeded FaultPlan over the same op sequence
  injects the SAME fault sequence — chaos is reproducible on demand, so
  every future PR can soak-test against an identical failure schedule.
- **FaultyEngine semantics**: errno/short-read/bit-flip/stuck/death each
  do exactly what the production failure they model does, through the
  full submit/wait API.
- **Retry policy**: transient-vs-permanent classification, exponential
  backoff under a per-gather budget, and recovery to byte-identical data.
- **Streamed parity**: a StreamingGather under injected EIO + short reads
  delivers output bit-identical to the fault-free read once retries
  succeed; engine death mid-gather recovers per-chunk on the fallback.
- **Breaker lifecycle**: closed → open on error rate → half-open probes
  after cooldown → closed on probe successes; a failed probe re-opens.
- **Hedged reads**: a chunk quiet past the adaptive threshold is re-read
  on the fallback, first completion wins, the stuck loser is cancelled.
- **Deadlines fail fast**: a deadline-carrying request over a wedged
  engine raises DeadlineExceeded well inside the old 30 s hang and mints
  an errored exemplar (PR 8 store); a wedged engine without a deadline
  raises a diagnosable EngineStallError naming the stuck tags.
"""

import errno
import json
import time

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.buffers import alloc_aligned
from strom.delivery.core import StromContext
from strom.delivery.shard import Segment
from strom.engine.base import DeadlineExceeded, EngineError, EngineStallError
from strom.engine.python_engine import PythonEngine
from strom.engine.resilience import (CHAOS_BENCH_FIELDS, RESILIENCE_FIELDS,
                                     CircuitBreaker, HedgeController,
                                     RetryPolicy, classify_errno)
from strom.faults import FaultPlan, FaultRule, FaultyEngine

MiB = 1024 * 1024


def _decisions(plan: FaultPlan, ops):
    """The plan's decision per op (kind or None), in op order."""
    out = []
    for path, off, ln in ops:
        f = plan.decide(path=path, offset=off, length=ln)
        out.append(None if f is None else
                   (f.kind, f.keep_bytes, f.flip_offset, f.flip_mask))
    return out


class TestFaultPlanDeterminism:
    OPS = [(f"/data/shard{i % 3}.bin", (i * 7919) % (64 * MiB), 128 * 1024)
           for i in range(400)]

    def test_same_seed_same_sequence(self):
        a = _decisions(FaultPlan.chaos(seed=42), self.OPS)
        b = _decisions(FaultPlan.chaos(seed=42), self.OPS)
        assert a == b
        assert any(d is not None for d in a), \
            "a 400-op chaos stream must inject something"

    def test_different_seed_different_sequence(self):
        a = _decisions(FaultPlan.chaos(seed=1), self.OPS)
        b = _decisions(FaultPlan.chaos(seed=2), self.OPS)
        assert a != b

    def test_stats_count_injections(self):
        plan = FaultPlan.chaos(seed=7)
        decided = _decisions(plan, self.OPS)
        s = plan.stats()
        assert s["ops_seen"] == len(self.OPS)
        assert s["faults_injected"] == sum(d is not None for d in decided)
        assert s["seed"] == 7

    def test_matchers(self):
        plan = FaultPlan([
            FaultRule("errno", path="shard1", err="EIO", times=2),
            FaultRule("short_read", offset_lo=MiB, offset_hi=2 * MiB),
        ], seed=0)
        # path matcher: shard0 ops below 1MiB never match either rule
        assert plan.decide(path="/d/shard0", offset=0, length=4096) is None
        # first matching rule wins, errno resolved from its name
        f = plan.decide(path="/d/shard1", offset=0, length=4096)
        assert f.kind == "errno" and f.err == errno.EIO
        # offset windows OVERLAP [lo, hi)
        f = plan.decide(path="/d/shard0", offset=MiB - 100, length=4096)
        assert f.kind == "short_read" and 0 <= f.keep_bytes < 4096
        assert plan.decide(path="/d/shard0", offset=2 * MiB,
                           length=4096) is None
        # times cap: the errno rule has one injection left
        assert plan.decide(path="/d/shard1", offset=0,
                           length=4096).kind == "errno"
        f = plan.decide(path="/d/shard1", offset=MiB, length=4096)
        assert f is not None and f.kind == "short_read"

    def test_every_nth(self):
        plan = FaultPlan([FaultRule("errno", every=3)], seed=0)
        kinds = [None if plan.decide(path="p", offset=0, length=64) is None
                 else "errno" for _ in range(9)]
        assert kinds == [None, None, "errno"] * 3

    def test_unwind_restores_times_cap(self):
        """A rolled-back injection (queue-full partial accept: the op
        never ran) un-counts the rule's times-cap and the tallies, so
        the replayed op re-decides against an unspent budget."""
        plan = FaultPlan([FaultRule("errno", times=1)], seed=0)
        f = plan.decide(path="p", offset=0, length=64)
        assert f is not None
        assert plan.decide(path="p", offset=0, length=64) is None
        plan.unwind(f)
        assert plan.stats()["faults_injected"] == 0
        f2 = plan.decide(path="p", offset=0, length=64)
        assert f2 is not None and f2.kind == "errno"

    def test_from_spec_forms(self, tmp_path):
        assert FaultPlan.from_spec("chaos:9").seed == 9
        assert FaultPlan.from_spec("chaos").seed == 0
        doc = {"seed": 3, "rules": [{"kind": "errno", "err": "ENXIO"}]}
        inline = FaultPlan.from_spec(json.dumps(doc))
        assert inline.seed == 3 and inline.rules[0].err == errno.ENXIO
        p = tmp_path / "plan.json"
        p.write_text(json.dumps(doc))
        assert FaultPlan.from_spec(str(p)).seed == 3
        with pytest.raises(ValueError):
            FaultPlan.from_spec("no-such-preset")
        with pytest.raises(ValueError):
            FaultRule("gamma_ray")


@pytest.fixture()
def faulty(data_file):
    """(FaultyEngine-over-python factory, path, golden). The factory takes
    the plan (and config overrides) so each test states its chaos."""
    path, golden = data_file
    engines = []

    def make(plan: FaultPlan, **cfg_kw) -> FaultyEngine:
        cfg_kw.setdefault("io_retry_backoff_s", 0.001)
        cfg_kw.setdefault("io_retry_backoff_max_s", 0.004)
        cfg = StromConfig(engine="python", queue_depth=8, num_buffers=8,
                          **cfg_kw)
        eng = FaultyEngine(PythonEngine(cfg), plan)
        engines.append(eng)
        return eng

    yield make, path, golden
    for eng in engines:
        eng.close()


class TestFaultyEngine:
    def test_transient_errno_absorbed_by_retry(self, faulty):
        make, path, golden = faulty
        eng = make(FaultPlan([FaultRule("errno", times=2)], seed=0))
        fi = eng.register_file(path)
        dest = alloc_aligned(MiB)
        n = eng.read_vectored([(fi, 0, 0, MiB)], dest, retries=3)
        assert n == MiB
        np.testing.assert_array_equal(dest, golden[:MiB])
        assert eng.plan.stats()["faults_injected"] == 2

    def test_short_read_retried_to_full_bytes(self, faulty):
        make, path, golden = faulty
        eng = make(FaultPlan([FaultRule("short_read", times=2,
                                        short_frac=0.25)], seed=0))
        fi = eng.register_file(path)
        dest = alloc_aligned(MiB)
        n = eng.read_vectored([(fi, 0, 0, MiB)], dest, retries=3)
        assert n == MiB
        np.testing.assert_array_equal(dest, golden[:MiB])

    def test_bit_flip_is_silent_corruption(self, faulty):
        make, path, golden = faulty
        eng = make(FaultPlan([FaultRule("bit_flip", times=1)], seed=5))
        fi = eng.register_file(path)
        dest = alloc_aligned(256 * 1024)
        n = eng.read_vectored([(fi, 0, 0, 256 * 1024)], dest, retries=1)
        assert n == 256 * 1024  # reported success: that's the point
        diff = np.nonzero(dest != golden[:256 * 1024])[0]
        assert len(diff) == 1, "exactly one corrupted byte"
        assert bin(int(dest[diff[0]]) ^ int(golden[diff[0]])).count("1") == 1

    def test_permanent_errno_fails_immediately(self, faulty):
        make, path, golden = faulty
        eng = make(FaultPlan([FaultRule("errno", err="EBADF")], seed=0))
        fi = eng.register_file(path)
        dest = alloc_aligned(128 * 1024)
        with pytest.raises(EngineError) as ei:
            eng.read_vectored([(fi, 0, 0, 128 * 1024)], dest, retries=5)
        assert ei.value.errno == errno.EBADF
        # no resubmit for a permanent errno: one op seen, one injected
        assert eng.plan.stats()["ops_seen"] == 1

    def test_engine_death_latches(self, faulty):
        make, path, golden = faulty
        eng = make(FaultPlan([FaultRule("engine_death", op_lo=1)], seed=0),
                   io_retry_budget=4)
        fi = eng.register_file(path)
        dest = alloc_aligned(128 * 1024)
        n = eng.read_vectored([(fi, 0, 0, 128 * 1024)], dest, retries=1)
        assert n == 128 * 1024  # op 0 passes through
        with pytest.raises(EngineError):
            eng.read_vectored([(fi, 0, 0, 128 * 1024)], dest, retries=2)
        assert eng.plan.dead
        # dead is dead: every later op fails instantly too
        with pytest.raises(EngineError):
            eng.read_vectored([(fi, 0, 0, 128 * 1024)], dest, retries=0)

    def test_latency_spike_delays_but_delivers(self, faulty):
        make, path, golden = faulty
        eng = make(FaultPlan([FaultRule("latency", latency_s=0.05,
                                        times=1)], seed=0))
        fi = eng.register_file(path)
        dest = alloc_aligned(128 * 1024)
        t0 = time.monotonic()
        n = eng.read_vectored([(fi, 0, 0, 128 * 1024)], dest, retries=1)
        assert n == 128 * 1024
        assert time.monotonic() - t0 >= 0.045
        np.testing.assert_array_equal(dest, golden[:128 * 1024])

    def test_stuck_released_by_cancel(self, faulty):
        make, path, golden = faulty
        eng = make(FaultPlan([FaultRule("stuck")], seed=0))
        fi = eng.register_file(path)
        dest = alloc_aligned(64 * 1024)
        tok = eng.submit_vectored([(fi, 0, 0, 64 * 1024)], dest, retries=0)
        assert eng.poll(tok, min_completions=1, timeout_s=0.2) == []
        eng.cancel(tok, timeout_s=2.0)  # releases the stuck op as ECANCELED
        assert eng.in_flight() == 0


class TestRetryPolicy:
    def test_classification(self):
        for e in (errno.EIO, errno.EAGAIN, errno.ETIMEDOUT, errno.ENODATA):
            assert classify_errno(e) == "transient"
        for e in (errno.EBADF, errno.EINVAL, errno.ECANCELED, errno.EACCES):
            assert classify_errno(e) == "permanent"
        assert classify_errno(-errno.EIO) == "transient"  # sign-agnostic
        assert classify_errno(12345) == "transient"  # unknown: optimism

    def test_backoff_exponential_and_capped(self):
        pol = RetryPolicy(backoff_s=0.01, backoff_max_s=0.05, jitter=0.0)
        assert pol.delay_s(0) == pytest.approx(0.01)
        assert pol.delay_s(1) == pytest.approx(0.02)
        assert pol.delay_s(2) == pytest.approx(0.04)
        assert pol.delay_s(5) == pytest.approx(0.05)  # capped

    def test_jitter_bounded(self):
        pol = RetryPolicy(backoff_s=0.01, backoff_max_s=1.0, jitter=0.5)
        for a in range(4):
            base = 0.01 * 2 ** a
            for _ in range(20):
                assert base <= pol.delay_s(a) <= base * 1.5 + 1e-12

    def test_should_retry_gates(self):
        pol = RetryPolicy(budget=2)
        assert pol.should_retry(errno.EIO, 0, 3, 0)
        assert not pol.should_retry(errno.EBADF, 0, 3, 0)  # permanent
        assert not pol.should_retry(errno.EIO, 3, 3, 0)    # attempts spent
        assert not pol.should_retry(errno.EIO, 0, 3, 2)    # budget spent

    def test_gather_budget_bounds_retry_storm(self, faulty):
        """A persistently sick extent stops retrying at the per-gather
        budget — bounded resubmits, then the error surfaces."""
        make, path, golden = faulty
        eng = make(FaultPlan([FaultRule("errno")], seed=0),
                   io_retry_budget=3)
        fi = eng.register_file(path)
        dest = alloc_aligned(64 * 1024)
        with pytest.raises(EngineError):
            eng.read_vectored([(fi, 0, 0, 64 * 1024)], dest, retries=100)
        # 1 original + exactly budget resubmits reached the plan
        assert eng.plan.stats()["ops_seen"] == 4


def _ctx(path=None, **cfg_kw):
    cfg_kw.setdefault("engine", "python")
    cfg_kw.setdefault("queue_depth", 8)
    cfg_kw.setdefault("num_buffers", 16)
    cfg_kw.setdefault("io_retry_backoff_s", 0.001)
    cfg_kw.setdefault("io_retry_backoff_max_s", 0.004)
    cfg_kw.setdefault("hot_cache_bytes", 0)
    return StromContext(StromConfig(**cfg_kw))


def _stream_read(ctx, path, nbytes) -> np.ndarray:
    dest = alloc_aligned(nbytes)
    g = ctx.stream_segments(path, [Segment(0, 0, nbytes)], dest)
    try:
        while not g.done:
            g.poll(min_completions=1, timeout_s=0.5)
        g.finish()
    finally:
        g.close()
    return dest


class TestStreamedParityUnderFaults:
    def test_bit_identical_under_eio_and_short_reads(self, data_file):
        """The acceptance bit: injected EIO + short reads + latency spikes,
        streamed output identical to the fault-free bytes."""
        path, golden = data_file
        plan = json.dumps({"seed": 11, "rules": [
            {"kind": "errno", "every": 5, "times": 3},
            {"kind": "short_read", "every": 7, "times": 3,
             "short_frac": 0.5},
            {"kind": "latency", "every": 11, "times": 2,
             "latency_s": 0.005},
        ]})
        ctx = _ctx(fault_plan=plan, io_retries=3)
        try:
            dest = _stream_read(ctx, path, 2 * MiB)
            np.testing.assert_array_equal(dest, golden[:2 * MiB])
            res = ctx.stats(sections=("resilience",))["resilience"]
            assert res["faults_injected"] >= 6
            assert res["chunk_retries"] >= 4
            assert res["fault_plan"]["by_kind"]["errno"] == 3
        finally:
            ctx.close()

    def test_engine_death_recovers_per_chunk_on_fallback(self, data_file):
        """fail_fast=False + per-chunk failover: the engine dying mid-batch
        no longer kills the gather — unserved chunks re-read on the python
        fallback path, output stays golden, counters say failover did it."""
        path, golden = data_file
        plan = json.dumps({"seed": 0, "rules": [
            {"kind": "engine_death", "op_lo": 4}]})
        ctx = _ctx(fault_plan=plan, io_retries=1, io_retry_budget=4,
                   breaker_min_events=2)
        try:
            dest = _stream_read(ctx, path, 2 * MiB)
            np.testing.assert_array_equal(dest, golden[:2 * MiB])
            res = ctx.stats(sections=("resilience",))["resilience"]
            assert res["failover_reads"] > 0
            assert res["failover_bytes"] > 0
            assert res["fault_plan"]["engine_dead"] is True
        finally:
            ctx.close()

    def test_demand_path_breaker_failover(self, data_file):
        """pread over a dead engine: the gather that trips the breaker
        reroutes to the fallback and SERVES; while open, primary is never
        touched; /stats shows the open breaker."""
        path, golden = data_file
        plan = json.dumps({"seed": 0, "rules": [{"kind": "engine_death"}]})
        ctx = _ctx(fault_plan=plan, io_retries=1, io_retry_budget=2,
                   breaker_min_events=2, breaker_error_rate=0.5,
                   breaker_cooldown_s=60.0)
        try:
            # failure 1: breaker still closed (below min_events) — propagates
            with pytest.raises(EngineError):
                ctx.pread(path, 0, 256 * 1024)
            # failure 2 trips it OPEN: THIS gather reroutes and serves
            out = ctx.pread(path, 0, 256 * 1024)
            np.testing.assert_array_equal(out[:256 * 1024],
                                          golden[:256 * 1024])
            res = ctx.stats(sections=("resilience",))["resilience"]
            assert res["state"] == "open"
            assert res["breaker_trips"] == 1
            ops_before = ctx.engine.plan.stats()["ops_seen"]
            # while open: straight to fallback, primary untouched
            out = ctx.pread(path, MiB, 128 * 1024)
            np.testing.assert_array_equal(
                out[:128 * 1024], golden[MiB:MiB + 128 * 1024])
            assert ctx.engine.plan.stats()["ops_seen"] == ops_before
        finally:
            ctx.close()


class TestBreakerGranularity:
    def test_streamed_gather_feeds_breaker_once(self, data_file):
        """Per-GATHER breaker outcomes on the streamed path: a batch with
        several recovered chunks is ONE failure (a handful of recoveries
        in a 10^4-chunk batch must not read as a 100% error rate to the
        rolling window), and a clean gather is one success."""
        path, golden = data_file
        plan = json.dumps({"seed": 0, "rules": [
            {"kind": "errno", "every": 2, "times": 3}]})
        ctx = _ctx(fault_plan=plan, io_retries=0, breaker_min_events=100)
        try:
            dest = _stream_read(ctx, path, 2 * MiB)
            np.testing.assert_array_equal(dest, golden[:2 * MiB])
            info = ctx.resilience.breaker.info()
            assert info["window_events"] == 1, info
            assert info["window_failures"] == 1, info
            dest = _stream_read(ctx, path, MiB)  # plan exhausted: clean
            np.testing.assert_array_equal(dest, golden[:MiB])
            info = ctx.resilience.breaker.info()
            assert info["window_events"] == 2, info
            assert info["window_failures"] == 1, info
        finally:
            ctx.close()


class TestBreakerLifecycle:
    def make(self, **kw):
        self.now = [0.0]
        kw.setdefault("window_s", 10.0)
        kw.setdefault("min_events", 4)
        kw.setdefault("error_rate", 0.5)
        kw.setdefault("cooldown_s", 5.0)
        kw.setdefault("half_open_successes", 2)
        return CircuitBreaker(clock=lambda: self.now[0], **kw)

    def test_trip_half_open_recover(self):
        br = self.make()
        trips = []
        br.on_trip = trips.append
        for _ in range(3):
            br.record_success()
        assert br.state == CircuitBreaker.CLOSED and br.allow()
        # 4 failures out of the last 7 ≥ 50% over ≥ min_events: OPEN
        for _ in range(4):
            br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        assert br.trips == 1 and len(trips) == 1
        assert not br.allow(), "open + inside cooldown: reroute"
        # cooldown elapses: next allow() is a HALF_OPEN probe
        self.now[0] += 5.1
        assert br.allow()
        assert br.state == CircuitBreaker.HALF_OPEN
        br.record_success()
        assert br.state == CircuitBreaker.HALF_OPEN  # 1 of 2
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED
        assert br.recoveries == 1
        assert br.allow()

    def test_failed_probe_reopens(self):
        br = self.make()
        for _ in range(4):
            br.record_failure()
        self.now[0] += 5.1
        assert br.allow()  # half-open probe
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        assert br.trips == 2
        assert not br.allow(), "cooldown restarted by the failed probe"

    def test_window_prunes_stale_failures(self):
        br = self.make()
        for _ in range(3):
            br.record_failure()
        self.now[0] += 11.0  # stale: outside the 10 s window
        for _ in range(4):
            br.record_success()
        br.record_failure()  # 1 failure / 5 events < 50%
        assert br.state == CircuitBreaker.CLOSED

    def test_info_shape(self):
        br = self.make()
        info = br.info()
        assert info["state"] == "closed" and info["breaker_state"] == 0
        for k in ("breaker_trips", "breaker_probes", "breaker_recoveries"):
            assert k in info


class TestHedgedReads:
    def test_threshold_floors_on_cold_window(self):
        h = HedgeController(min_s=0.05, multiplier=3.0)
        assert h.threshold_s() == pytest.approx(0.05)
        for _ in range(7):
            h.observe(10.0)  # < 8 observations: still the floor
        assert h.threshold_s() == pytest.approx(0.05)

    def test_threshold_tracks_rolling_p99(self):
        h = HedgeController(min_s=0.001, multiplier=2.0)
        for _ in range(128):
            h.observe(0.01)
        assert h.threshold_s() == pytest.approx(0.02, rel=0.01)

    def test_hedge_first_wins_loser_cancelled(self, data_file):
        """A chunk stuck on the primary past the hedge threshold is served
        by the fallback (hedges_fired/won count it); finish() cancels the
        stuck loser and the batch is bit-identical."""
        path, golden = data_file
        plan = json.dumps({"seed": 0, "rules": [
            {"kind": "stuck", "times": 1}]})
        ctx = _ctx(fault_plan=plan, hedge_min_s=0.05, hedge_multiplier=0.0)
        try:
            t0 = time.monotonic()
            dest = _stream_read(ctx, path, MiB)
            assert time.monotonic() - t0 < 10.0, \
                "hedge must beat any stall watchdog by an order of magnitude"
            np.testing.assert_array_equal(dest, golden[:MiB])
            res = ctx.stats(sections=("resilience",))["resilience"]
            assert res["hedges_fired"] >= 1
            assert res["hedges_won"] >= 1
            assert ctx.engine.in_flight() == 0, "loser reaped by cancel"
        finally:
            ctx.close()

    def test_loser_completion_not_reemitted(self, data_file):
        """The hedged range reaches the consumer exactly once: the losing
        primary completion arriving later is discarded (a duplicate range
        would double-decrement the pump's per-sample byte countdown and
        wedge the batch)."""
        path, golden = data_file
        plan = json.dumps({"seed": 0, "rules": [
            {"kind": "latency", "times": 1, "latency_s": 0.2}]})
        ctx = _ctx(fault_plan=plan, hedge_min_s=0.03, hedge_multiplier=0.0)
        try:
            dest = alloc_aligned(MiB)
            g = ctx.stream_segments(path, [Segment(0, 0, MiB)], dest)
            ranges = []
            try:
                t_end = time.monotonic() + 10.0
                while not g.done and time.monotonic() < t_end:
                    ranges.extend(g.poll(min_completions=1, timeout_s=0.25))
                time.sleep(0.25)  # let the latency-held loser release
                ranges.extend(g.poll(min_completions=0))
                g.finish()
            finally:
                g.close()
            assert len(ranges) == len(set(ranges)), \
                f"duplicate dest range emitted: {sorted(ranges)}"
            assert sum(hi - lo for lo, hi in ranges) == MiB
            np.testing.assert_array_equal(dest, golden[:MiB])
        finally:
            ctx.close()

    def test_hedge_fires_once_per_chunk(self, data_file):
        """A straggler whose fallback read cannot serve it must not
        re-hedge on every poll (a hedge storm through the serialized
        lifeboat and a meaningless hedges_fired count)."""
        path, _ = data_file
        plan = json.dumps({"seed": 0, "rules": [{"kind": "stuck"}]})
        ctx = _ctx(fault_plan=plan, hedge_min_s=0.02, hedge_multiplier=0.0,
                   breaker_enabled=False)
        try:
            # the section reads the process-global registry: delta it
            fired0 = ctx.stats(
                sections=("resilience",))["resilience"]["hedges_fired"]
            dest = alloc_aligned(128 * 1024)
            g = ctx.stream_segments(path, [Segment(0, 0, 128 * 1024)], dest)
            try:
                # a fallback that can never serve: every hedge misses, the
                # chunks stay unaccounted across many polls
                ctx.resilience.read_chunk_fallback = lambda *a, **k: False
                nchunks = len(g._chunks)
                deadline = time.monotonic() + 1.0
                while time.monotonic() < deadline:
                    g.poll(min_completions=1, timeout_s=0.05)
            finally:
                g.close()
            fired = ctx.stats(
                sections=("resilience",))["resilience"]["hedges_fired"]
            assert fired - fired0 == nchunks, \
                "a missed hedge must not refire on every poll"
        finally:
            ctx.close()

    def test_zero_hedge_params_disable_hedging(self):
        """hedge_min_s=0 + hedge_multiplier=0 is the documented OFF
        spelling — it must not become a 0-threshold hedge-everything."""
        ctx = _ctx(hedge_min_s=0.0, hedge_multiplier=0.0)
        try:
            assert ctx.resilience.hedge is None
        finally:
            ctx.close()

    def test_primary_win_counts_wasted_bytes(self, data_file):
        """When the primary completes while the hedge is in flight, the
        hedge's bytes are counted wasted and the primary's data stands."""
        path, golden = data_file
        plan = json.dumps({"seed": 0, "rules": [
            {"kind": "latency", "times": 1, "latency_s": 0.15}]})
        ctx = _ctx(fault_plan=plan, hedge_min_s=0.03, hedge_multiplier=0.0)
        try:
            dest = _stream_read(ctx, path, MiB)
            np.testing.assert_array_equal(dest, golden[:MiB])
            res = ctx.stats(sections=("resilience",))["resilience"]
            assert res["hedges_fired"] >= 1
        finally:
            ctx.close()


class TestDeadlines:
    def test_deadline_fails_fast_and_mints_errored_exemplar(self, data_file):
        """The acceptance bit: a deadline-carrying request over a wedged
        engine fails in ~deadline seconds — not the legacy 30 s — with the
        typed error, a deadline_exceeded count, and an errored exemplar
        retained in the PR 8 store."""
        from strom.obs.exemplars import store

        path, _ = data_file
        plan = json.dumps({"seed": 0, "rules": [{"kind": "stuck"}]})
        ctx = _ctx(fault_plan=plan, breaker_enabled=False)
        try:
            store.clear()
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                ctx.pread(path, 0, 256 * 1024, tenant="t9", deadline_s=0.4)
            elapsed = time.monotonic() - t0
            assert elapsed < 5.0, f"fail-fast took {elapsed:.1f}s"
            res = ctx.stats(sections=("resilience",))["resilience"]
            assert res["deadline_exceeded"] >= 1
            kept = store.exemplars("t9")
            assert any(e["error"] and "deadline" in e["error"].lower()
                       for e in kept), f"errored exemplar missing: {kept}"
        finally:
            ctx.close()

    def test_config_default_deadline_applies(self, data_file):
        path, _ = data_file
        plan = json.dumps({"seed": 0, "rules": [{"kind": "stuck"}]})
        ctx = _ctx(fault_plan=plan, request_deadline_s=0.3,
                   breaker_enabled=False)
        try:
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                ctx.pread(path, 0, 128 * 1024)
            assert time.monotonic() - t0 < 5.0
        finally:
            ctx.close()

    def test_no_deadline_stall_raises_diagnosable_error(self, data_file):
        """Without a deadline, a wedged engine raises EngineStallError at
        the configured watchdog — naming the stuck tags — instead of
        looping silently for a hard-coded 30 s."""
        path, _ = data_file
        cfg = StromConfig(engine="python", queue_depth=8, num_buffers=8,
                          engine_wait_timeout_s=0.3)
        eng = FaultyEngine(
            PythonEngine(cfg),
            FaultPlan([FaultRule("stuck")], seed=0))
        try:
            fi = eng.register_file(path)
            dest = alloc_aligned(64 * 1024)
            t0 = time.monotonic()
            with pytest.raises(EngineStallError) as ei:
                eng.read_vectored([(fi, 0, 0, 64 * 1024)], dest, retries=0)
            assert time.monotonic() - t0 < 5.0
            assert ei.value.stuck_tags, "the stuck tags are the diagnosis"
            assert ei.value.errno == errno.ETIMEDOUT
        finally:
            eng.close()

    def test_stream_poll_stall_raises(self, data_file):
        """The pipeline pump loop polls in short slices, so the ENGINE
        watchdog can never fire from it — the gather-level watchdog in
        StreamingGather.poll must turn a wedged engine into a diagnosable
        EngineStallError instead of a silent forever-hang."""
        path, _ = data_file
        plan = json.dumps({"seed": 0, "rules": [{"kind": "stuck"}]})
        ctx = _ctx(fault_plan=plan, engine_wait_timeout_s=0.3,
                   hedge_enabled=False, breaker_enabled=False)
        try:
            dest = alloc_aligned(64 * 1024)
            g = ctx.stream_segments(path, [Segment(0, 0, 64 * 1024)], dest)
            try:
                t0 = time.monotonic()
                with pytest.raises(EngineStallError):
                    while not g.done and time.monotonic() - t0 < 5.0:
                        g.poll(min_completions=1, timeout_s=0.05)
                assert time.monotonic() - t0 < 5.0
            finally:
                g.close()
        finally:
            ctx.close()

    def test_deadline_in_poll_path(self, data_file):
        """The async token honors the deadline too: poll stops waiting and
        the token fails fast with DeadlineExceeded."""
        path, _ = data_file
        cfg = StromConfig(engine="python", queue_depth=8, num_buffers=8)
        eng = FaultyEngine(
            PythonEngine(cfg), FaultPlan([FaultRule("stuck")], seed=0))
        try:
            fi = eng.register_file(path)
            dest = alloc_aligned(64 * 1024)
            tok = eng.submit_vectored(
                [(fi, 0, 0, 64 * 1024)], dest, retries=0,
                deadline=time.monotonic() + 0.2)
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                eng.drain(tok)
            assert time.monotonic() - t0 < 5.0
            eng.cancel(tok, timeout_s=2.0)
        finally:
            eng.close()


class TestMultiRingQuarantine:
    def test_transient_errors_quarantine_a_ring(self):
        """Unit contract for MultiRingEngine degradation: repeated
        transient failures pull a member from the rotation (while a
        healthy peer remains) and the degraded state is visible."""
        pytest.importorskip("strom.engine.uring_engine")
        from strom.engine.uring_engine import uring_available

        if not uring_available():
            pytest.skip("io_uring unavailable")
        from strom.engine import make_engine

        eng = make_engine(StromConfig(engine="uring", engine_rings=2,
                                      breaker_min_events=2))
        try:
            e = EngineError(errno.EIO, "injected")
            eng._note_ring_error(0, e)
            assert eng._healthy_rings() == [0, 1]
            eng._note_ring_error(0, e)
            assert eng._healthy_rings() == [1]
            s = eng.stats()
            assert s["quarantined_rings"] == [0]
            assert s["ring_errors"][0] == 2
            # stable remap: a healthy home ring keeps its files, only the
            # quarantined ring's files redirect to a survivor
            assert eng._route(1, eng._healthy_rings()) == 1
            assert eng._route(0, eng._healthy_rings()) == 1
            # EOF/short-read is data-dependent, never a ring fault
            eng._note_ring_error(1, EngineError(errno.ENODATA, "eof"))
            eng._note_ring_error(1, EngineError(errno.ENODATA, "eof"))
            assert eng._healthy_rings() == [1]
            # permanent errors never quarantine (retry would fail anywhere)
            eng._note_ring_error(1, EngineError(errno.EBADF, "x"))
            eng._note_ring_error(1, EngineError(errno.EBADF, "x"))
            assert eng._healthy_rings() == [1]
        finally:
            eng.close()


class TestResilienceSurfaces:
    def test_stats_section_covers_resilience_fields(self, data_file):
        """Every RESILIENCE_FIELDS key is present in /stats["resilience"]
        — the producer side of the bench-column / compare_rounds parity."""
        ctx = _ctx()
        try:
            res = ctx.stats(sections=("resilience",))["resilience"]
            for k in RESILIENCE_FIELDS:
                assert k in res, f"missing {k}"
        finally:
            ctx.close()

    def test_chaos_fields_match_cli_arm_keys(self):
        """CHAOS_BENCH_FIELDS (the producer tuple cli.bench_chaos emits)
        and the compare_rounds resilience section must agree — a rename on
        either side is a silently dead column."""
        import tools.compare_rounds as cr

        assert list(CHAOS_BENCH_FIELDS) == list(cr.RESIL_KEYS)

    def test_tenants_page_shows_degraded_state(self, data_file):
        ctx = _ctx()
        try:
            rows = ctx.scheduler.tenants_info()
            assert "resilience" in rows
            assert "breaker_state" in rows["resilience"]
        finally:
            ctx.close()

    def test_fallback_engine_lazy(self, data_file):
        """The lifeboat (a second buffer pool + worker threads) costs
        nothing until a read actually fails over — healthy demand reads
        must not build it."""
        path, golden = data_file
        ctx = _ctx()
        try:
            out = ctx.pread(path, 0, 128 * 1024)
            np.testing.assert_array_equal(out[:128 * 1024],
                                          golden[:128 * 1024])
            assert ctx.resilience._fb is None
        finally:
            ctx.close()

    def test_lint_covers_resilience_tuples(self):
        """tools/lint_stats_names.py must scan RESILIENCE_FIELDS /
        CHAOS_BENCH_FIELDS / RESIL_KEYS literals (they name the same series
        the producers feed), so a restyled spelling collides at lint time."""
        import os

        from tools.lint_stats_names import scan_sources

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        found, _ = scan_sources(root)
        for name in ("chunk_retries", "hedges_won", "breaker_trips",
                     "chaos_ok", "chaos_slowdown", "failover_bytes"):
            norm = name.replace("_", "").lower()
            assert norm in found, f"lint does not see {name}"


class TestOpMatchers:
    """ISSUE 13 satellite: read/write direction matchers on fault rules —
    presets tuned against read streams must not silently double-count once
    writes share the engine."""

    def test_op_matcher_scopes_rules(self):
        plan = FaultPlan([FaultRule("errno", op="read", p=1.0),
                          FaultRule("errno", op="write", err="ENOSPC",
                                    p=1.0)], seed=0)
        f = plan.decide(path="/d/x", offset=0, length=4096, op="read")
        assert f is not None and f.err == errno.EIO
        f = plan.decide(path="/d/x", offset=0, length=4096, op="write")
        assert f is not None and f.err == errno.ENOSPC

    def test_unscoped_rule_matches_both(self):
        plan = FaultPlan([FaultRule("errno", p=1.0)], seed=0)
        assert plan.decide(path=None, offset=0, length=1, op="read") \
            is not None
        assert plan.decide(path=None, offset=0, length=1, op="write") \
            is not None

    def test_mismatched_op_consumes_no_rng_draw(self):
        """A read-scoped p<1 rule evaluated against write traffic must not
        advance the plan RNG: the read stream's injected sequence is
        identical with or without interleaved writes (the double-count
        fix)."""
        ops = [(f"/d/s{i % 2}", i * 4096, 4096) for i in range(200)]
        a = FaultPlan([FaultRule("errno", op="read", p=0.1)], seed=3)
        plain = [a.decide(path=p, offset=o, length=ln, op="read")
                 is not None for p, o, ln in ops]
        b = FaultPlan([FaultRule("errno", op="read", p=0.1)], seed=3)
        mixed = []
        for p, o, ln in ops:
            b.decide(path=p, offset=o, length=ln, op="write")  # interleave
            mixed.append(b.decide(path=p, offset=o, length=ln, op="read")
                         is not None)
        assert plain == mixed

    def test_bit_flip_never_matches_writes(self):
        plan = FaultPlan([FaultRule("bit_flip", p=1.0)], seed=0)
        assert plan.decide(path=None, offset=0, length=64,
                           op="write") is None
        assert plan.decide(path=None, offset=0, length=64,
                           op="read") is not None

    def test_chaos_preset_is_read_scoped(self):
        plan = FaultPlan.chaos(seed=0)
        assert all(r.op == "read" for r in plan.rules)
        for i in range(200):
            assert plan.decide(path="/d/w", offset=i * 4096, length=4096,
                               op="write") is None
        assert plan.stats()["faults_injected"] == 0

    def test_chaos_writes_preset(self):
        plan = FaultPlan.from_spec("chaos_writes:5")
        assert all(r.op == "write" for r in plan.rules)
        assert plan.seed == 5
        hits = sum(plan.decide(path="/d/w", offset=i * 4096, length=4096,
                               op="write") is not None for i in range(400))
        assert hits > 0

    def test_bad_op_matcher_rejected(self):
        with pytest.raises(ValueError):
            FaultRule("errno", op="sideways")
