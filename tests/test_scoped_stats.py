"""Scoped telemetry (ISSUE 6 tentpole): label-scoped StatsRegistry views.

The multi-tenant invariant under test everywhere here: a write through a
scope lands in BOTH the scoped series and the aggregate, so per-scope
series render as Prometheus labels while the unlabeled aggregate equals
the sum of its scopes — under concurrency, through every series kind, and
end to end through a StromContext serving /metrics.
"""

import threading
import urllib.request

import numpy as np
import pytest

from strom.utils.stats import (ScopedStats, StatsRegistry, format_labels,
                               global_stats)


def fresh():
    return StatsRegistry("t")


class TestScopedRegistry:
    def test_identity_scope(self):
        r = fresh()
        assert r.scoped() is r
        assert r.scoped(tenant=None) is r  # None labels drop out

    def test_same_labels_share_series(self):
        r = fresh()
        a = r.scoped(tenant="t0")
        b = r.scoped(tenant="t0")
        a.add("x", 2)
        b.add("x", 3)
        assert a.counter("x").value == 5
        assert r.counter("x").value == 5

    def test_counter_fans_to_aggregate(self):
        r = fresh()
        s = r.scoped(pipeline="resnet")
        s.add("bytes", 7)
        assert s.counter("bytes").value == 7
        assert r.counter("bytes").value == 7

    def test_gauge_and_histogram_fan(self):
        r = fresh()
        s = r.scoped(pipeline="vit")
        s.set_gauge("depth", 4)
        s.gauge("peak").max(9)
        s.observe_us("lat", 100.0)
        with s.timer_us("lat"):
            pass
        assert r.gauge("depth").value == 4
        assert r.gauge("peak").value == 9
        assert r.histogram("lat").count == 2
        assert s.histogram("lat").count == 2

    def test_refinement_merges_labels(self):
        r = fresh()
        t = r.scoped(tenant="t0")
        p = t.scoped(pipeline="resnet")
        assert p.labels == {"tenant": "t0", "pipeline": "resnet"}
        p.add("x")
        # lands in the refined scope + aggregate, NOT the parent scope
        assert r.counter("x").value == 1
        assert t.counter("x").value == 0
        assert p.counter("x").value == 1

    def test_label_str_canonical(self):
        r = fresh()
        s = r.scoped(b="2", a="1")
        assert s.label_str == 'a="1",b="2"'
        assert format_labels({"q": 'say "hi"'}) == r'q="say \"hi\""'

    def test_counter_typing_flows_through_scope(self):
        """Names created through scopes register as counters in the
        aggregate too, so /metrics types the labeled series correctly."""
        r = fresh()
        r.scoped(t="0").add("my_counter")
        assert "my_counter" in r.counter_names()

    def test_concurrent_churn_aggregate_equals_sum(self):
        """The acceptance invariant: 4 threads x 2 scopes hammering the
        same names — aggregate == sum of scopes for counters AND
        histogram counts, no drops under the fan-out."""
        r = fresh()
        scopes = [r.scoped(pipeline="resnet", tenant="t0"),
                  r.scoped(pipeline="vit", tenant="t0")]
        n_iter = 2000

        def churn(scope):
            for i in range(n_iter):
                scope.add("ops")
                scope.add("bytes", 3)
                scope.observe_us("lat", float(i % 64 + 1))
                scope.gauge("depth").set(i)

        threads = [threading.Thread(target=churn, args=(s,))
                   for s in scopes for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert r.counter("ops").value == 8 * n_iter
        assert r.counter("bytes").value == 8 * n_iter * 3
        assert sum(s.counter("ops").value for s in scopes) \
            == r.counter("ops").value
        assert sum(s.counter("bytes").value for s in scopes) \
            == r.counter("bytes").value
        assert r.histogram("lat").count == 8 * n_iter
        assert sum(s.histogram("lat").count for s in scopes) \
            == r.histogram("lat").count
        # bucket-level identity, not just counts
        agg = r.histogram("lat").buckets
        summed = [a + b for a, b in zip(scopes[0].histogram("lat").buckets,
                                        scopes[1].histogram("lat").buckets)]
        assert agg == summed

    def test_scopes_snapshot(self):
        r = fresh()
        r.scoped(tenant="t0").add("x", 1)
        r.scoped(tenant="t1").add("x", 2)
        snaps = r.scopes_snapshot()
        assert snaps['tenant="t0"']["x"] == 1
        assert snaps['tenant="t1"']["x"] == 2

    def test_add_buckets_merge(self):
        """Bulk bucket merge (the uring native-gather mirror path) keeps
        count/total consistent on both halves of the fan."""
        r = fresh()
        s = r.scoped(tenant="t0")
        s.histogram("engine_op_lat").add_buckets([0, 2, 1], 300.0)
        assert r.histogram("engine_op_lat").count == 3
        assert s.histogram("engine_op_lat").count == 3
        assert r.histogram("engine_op_lat").total_us == 300.0


class TestScopedExposition:
    def test_labeled_samples_under_one_family(self):
        r = fresh()
        r.scoped(pipeline="resnet").add("ops", 2)
        r.scoped(pipeline="vit").add("ops", 3)
        text = r.prometheus()
        assert "# TYPE t_ops counter" in text
        assert text.count("# TYPE t_ops ") == 1  # one header per family
        assert "t_ops 5" in text
        assert 't_ops{pipeline="resnet"} 2' in text
        assert 't_ops{pipeline="vit"} 3' in text
        # unlabeled aggregate precedes labeled samples in the family block
        lines = text.splitlines()
        assert lines.index("t_ops 5") \
            < lines.index('t_ops{pipeline="resnet"} 2')

    def test_labeled_histograms(self):
        r = fresh()
        r.scoped(tenant="a").observe_us("lat", 100.0)
        r.scoped(tenant="b").observe_us("lat", 3.0)
        text = r.prometheus()
        assert text.count("# TYPE t_lat_us histogram") == 1
        assert 't_lat_us_bucket{le="128",tenant="a"} 1' in text
        assert 't_lat_us_count{tenant="a"} 1' in text
        assert 't_lat_us_count{tenant="b"} 1' in text
        assert "t_lat_us_count 2" in text
        # exact sums carried per scope
        assert 't_lat_us_sum{tenant="a"} 100.0' in text

    def test_no_scopes_no_labels(self):
        r = fresh()
        r.add("plain", 1)
        text = r.prometheus()
        assert "t_plain 1" in text
        assert "{" not in text.replace('le="', "")  # only histogram les


class TestContextScope:
    @pytest.fixture
    def ctx2(self, tmp_path):
        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        path = tmp_path / "f.bin"
        path.write_bytes(np.random.default_rng(0).bytes(1 << 20))
        cfg = StromConfig(engine="python", slab_pool_bytes=0)
        ctx = StromContext(cfg, metrics_port=0, scope={"tenant": "t9"})
        yield ctx, str(path)
        ctx.close()

    def test_context_scope_labels_delivery(self, ctx2):
        ctx, path = ctx2
        before = ctx.scope.counter("ssd2tpu_bytes").value
        ctx.memcpy_ssd2host(path, length=1 << 20)
        assert ctx.scope.counter("ssd2tpu_bytes").value - before == 1 << 20

    def test_engine_op_accounting_scoped(self, ctx2):
        ctx, path = ctx2
        h = ctx.scope.histogram("engine_op_lat")
        before = h.count
        ctx.memcpy_ssd2host(path, length=1 << 20)
        assert h.count > before  # per-op latency landed in the scope
        # the aggregate carries at least as much
        assert global_stats.histogram("engine_op_lat").count >= h.count

    def test_two_scopes_distinguishable_on_metrics(self, ctx2):
        """Acceptance shape: two pipelines' scopes on one context produce
        distinguishable labeled series on /metrics while the aggregate is
        their sum."""
        ctx, path = ctx2
        a = ctx.scope.scoped(pipeline="resnet")
        b = ctx.scope.scoped(pipeline="vit")
        base = global_stats.counter("t6_probe").value
        a.add("t6_probe", 2)
        b.add("t6_probe", 5)
        port = ctx.metrics_server.port
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics").read().decode()
        assert f'strom_t6_probe {base + 7}' in text
        assert 'strom_t6_probe{pipeline="resnet",tenant="t9"} 2' in text
        assert 'strom_t6_probe{pipeline="vit",tenant="t9"} 5' in text

    def test_stats_scopes_section(self, ctx2):
        ctx, path = ctx2
        ctx.scope.add("t6_probe2", 1)
        snap = ctx.stats()
        assert 'tenant="t9"' in snap["scopes"]
        assert snap["scopes"]['tenant="t9"']["t6_probe2"] == 1
        sub = ctx.stats(sections=["context"])
        assert set(sub) == {"context"}


class TestPipelineScopes:
    def test_prefetcher_scope(self):
        from strom.delivery.prefetch import Prefetcher

        r = fresh()
        s = r.scoped(pipeline="p0")
        pf = Prefetcher(iter([lambda: 1, lambda: 2]), depth=1, scope=s)
        assert list(pf) == [1, 2]
        assert s.gauge("prefetch_depth").value == 1

    def test_pipeline_steps_counter(self):
        """Pipeline.__next__ advances the scoped step heartbeat the flight
        recorder watches."""
        from strom.pipelines.base import Pipeline
        from strom.pipelines.sampler import EpochShuffleSampler

        r = fresh()
        s = r.scoped(pipeline="px")
        sampler = EpochShuffleSampler(8, 4, seed=0, shuffle=False)
        pipe = Pipeline(sampler, lambda idx, serial: len(idx), depth=1,
                        scope=s)
        next(pipe)
        next(pipe)
        pipe.close()
        assert s.counter("pipeline_steps").value == 2
        assert r.counter("pipeline_steps").value == 2
