"""NVMe spill tier (ISSUE 13 tentpole): demote-on-evict, interval serving,
refcounted slots, per-tenant accounting — and the end-to-end acceptance: a
warm-spill epoch serves evicted extents with ZERO source-engine reads
(spill_hit_bytes > 0, cache_miss_bytes = 0 on repeat traffic)."""

import os

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.delivery.hotcache import HotCache
from strom.delivery.spill import SPILL_FIELDS, SpillTier

KiB = 1024
MiB = 1024 * KiB


@pytest.fixture()
def tier(tmp_path):
    t = SpillTier(str(tmp_path / "spill.bin"), 8 * MiB)
    yield t
    t.close()


def _read(tier, skey, lo, hi) -> np.ndarray:
    out = np.zeros(hi - lo, dtype=np.uint8)
    hits, misses = tier.lookup(skey, lo, hi)
    assert not misses, misses
    try:
        for s, t, e in hits:
            tier.read_into(e, s, t, out[s - lo: t - lo])
    finally:
        tier.unpin([e for _, _, e in hits])
    return out


class TestSpillTierUnit:
    def test_offer_lookup_roundtrip(self, tier, rng):
        data = rng.integers(0, 256, 256 * KiB, dtype=np.uint8)
        assert tier.offer("k", 0, len(data), data) == len(data)
        np.testing.assert_array_equal(_read(tier, "k", 0, len(data)), data)
        # subrange serves by interval intersection
        np.testing.assert_array_equal(_read(tier, "k", 1000, 5000),
                                      data[1000:5000])

    def test_disjointness_skips_respilled_ranges(self, tier, rng):
        data = rng.integers(0, 256, 64 * KiB, dtype=np.uint8)
        assert tier.offer("k", 0, len(data), data) == len(data)
        # a re-evicted identical range: nothing new spilled
        assert tier.offer("k", 0, len(data), data) == 0
        # an overlapping wider range spills only the gaps
        wide = rng.integers(0, 256, 96 * KiB, dtype=np.uint8)
        wide[: len(data)] = data
        assert tier.offer("k", 0, len(wide), wide) == 32 * KiB

    def test_budget_evicts_oldest(self, tmp_path, rng):
        t = SpillTier(str(tmp_path / "s.bin"), 1 * MiB)
        try:
            blobs = {}
            for i in range(8):
                b = rng.integers(0, 256, 256 * KiB, dtype=np.uint8)
                blobs[i] = b
                t.offer(f"k{i}", 0, len(b), b)
            # budget holds 4 entries: the oldest dropped, newest serve
            assert t.bytes <= 1 * MiB
            hits, misses = t.lookup("k0", 0, 256 * KiB)
            t.unpin([e for _, _, e in hits])
            assert misses  # oldest gone
            np.testing.assert_array_equal(
                _read(t, "k7", 0, 256 * KiB), blobs[7])
        finally:
            t.close()

    def test_pinned_entry_not_evicted(self, tmp_path, rng):
        t = SpillTier(str(tmp_path / "p.bin"), 512 * KiB)
        try:
            a = rng.integers(0, 256, 256 * KiB, dtype=np.uint8)
            t.offer("a", 0, len(a), a)
            hits, _ = t.lookup("a", 0, len(a))
            # budget pressure while pinned: "a" survives (the other offer
            # is refused or evicts nothing — never the pinned entry)
            b = rng.integers(0, 256, 512 * KiB, dtype=np.uint8)
            t.offer("b", 0, len(b), b)
            for s, tt, e in hits:
                out = np.zeros(tt - s, dtype=np.uint8)
                t.read_into(e, s, tt, out)
                np.testing.assert_array_equal(out, a[s:tt])
            t.unpin([e for _, _, e in hits])
        finally:
            t.close()

    def test_slot_recycling(self, tmp_path, rng):
        """Evicted entries' file slots recycle — the spill file does not
        grow without bound under churn."""
        t = SpillTier(str(tmp_path / "r.bin"), 1 * MiB)
        try:
            for i in range(32):
                b = rng.integers(0, 256, 256 * KiB, dtype=np.uint8)
                t.offer(f"k{i}", 0, len(b), b)
            assert os.path.getsize(str(tmp_path / "r.bin")) <= 2 * MiB
        finally:
            t.close()

    def test_tenant_partition_self_evicts(self, tmp_path, rng):
        t = SpillTier(str(tmp_path / "t.bin"), 8 * MiB)
        try:
            t.set_partition("a", 512 * KiB)
            for i in range(4):
                b = rng.integers(0, 256, 256 * KiB, dtype=np.uint8)
                t.offer(f"a{i}", 0, len(b), b, tenant="a")
            parts = t.partitions()
            assert parts["a"]["bytes"] <= 512 * KiB
            # tenant b is untouched by a's churn
            bb = rng.integers(0, 256, 256 * KiB, dtype=np.uint8)
            t.offer("b0", 0, len(bb), bb, tenant="b")
            np.testing.assert_array_equal(_read(t, "b0", 0, len(bb)), bb)
        finally:
            t.close()

    def test_invalidate_drops_key(self, tier, rng):
        data = rng.integers(0, 256, 64 * KiB, dtype=np.uint8)
        tier.offer("k", 0, len(data), data)
        assert tier.invalidate("k") == 1
        _, misses = tier.lookup("k", 0, len(data))
        assert misses

    def test_stats_names_cover_fields(self, tier):
        snap = tier.stats()
        for k in ("spill_hit_bytes", "spill_hits", "spill_spilled_bytes",
                  "spill_entries", "spill_bytes", "spill_hit_ratio"):
            assert k in snap, k
        assert len(set(SPILL_FIELDS)) == len(SPILL_FIELDS)


class TestHotCacheDemotion:
    def _cache(self, tmp_path, cache_bytes=256 * KiB, spill_bytes=8 * MiB):
        cache = HotCache(cache_bytes, admit="always")
        cache.spill = SpillTier(str(tmp_path / "sp.bin"), spill_bytes)
        return cache

    def test_evicted_entry_demotes_and_serves(self, tmp_path, rng):
        cache = self._cache(tmp_path)
        data = [rng.integers(0, 256, 128 * KiB, dtype=np.uint8)
                for _ in range(4)]
        for i, b in enumerate(data):
            cache.admit(f"k{i}", 0, len(b), b)
        # budget ~2 entries: the early ones demoted, not vanished
        hits, misses = cache.spill.lookup("k0", 0, 128 * KiB)
        try:
            assert hits and not misses
            out = np.zeros(128 * KiB, dtype=np.uint8)
            for s, t, e in hits:
                cache.spill.read_into(e, s, t, out[s: t])
            np.testing.assert_array_equal(out, data[0])
        finally:
            cache.spill.unpin([e for _, _, e in hits])
        cache.spill.close()

    def test_clear_drops_without_demoting(self, tmp_path, rng):
        cache = self._cache(tmp_path)
        b = rng.integers(0, 256, 64 * KiB, dtype=np.uint8)
        cache.admit("k", 0, len(b), b)
        cache.clear()
        _, misses = cache.spill.lookup("k", 0, len(b))
        assert misses  # clear() drops, it does not spill
        cache.spill.close()

    def test_pinned_entry_never_evicted_under_pressure(self, tmp_path,
                                                       rng):
        """Budget eviction skips pinned entries entirely (the refcount
        contract): the reader's view stays valid, nothing demotes out from
        under it, and an oversized admission is refused instead."""
        cache = self._cache(tmp_path, cache_bytes=128 * KiB)
        a = rng.integers(0, 256, 64 * KiB, dtype=np.uint8)
        cache.admit("a", 0, len(a), a)
        hits, _, pins = cache.lookup("a", 0, len(a))
        assert pins
        b = rng.integers(0, 256, 128 * KiB, dtype=np.uint8)
        assert cache.admit("b", 0, len(b), b) == 0  # refused, not displaced
        for s, t, view in hits:
            np.testing.assert_array_equal(view, a[s:t])
        _, sp_miss = cache.spill.lookup("a", 0, len(a))
        assert sp_miss  # never evicted -> never demoted
        cache.unpin(pins)
        cache.spill.close()

    def test_cleared_while_pinned_frees_without_demoting(self, tmp_path,
                                                         rng):
        """clear() on a pinned entry: the slab frees on the LAST unpin and
        the bytes are dropped, not spilled (clear is a drop, the bench
        epoch scoping depends on it)."""
        cache = self._cache(tmp_path)
        a = rng.integers(0, 256, 64 * KiB, dtype=np.uint8)
        cache.admit("a", 0, len(a), a)
        hits, _, pins = cache.lookup("a", 0, len(a))
        cache.clear()
        for s, t, view in hits:  # readers keep a valid view until unpin
            np.testing.assert_array_equal(view, a[s:t])
        cache.unpin(pins)
        _, sp_miss = cache.spill.lookup("a", 0, len(a))
        assert sp_miss
        cache.spill.close()


class TestEndToEnd:
    @pytest.mark.parametrize("engine_io", [False, True])
    def test_warm_spill_epoch_zero_source_reads(self, tmp_path, rng,
                                                engine_io):
        """The ISSUE 13 acceptance, both spill I/O routes (ISSUE 14 A/B
        flag): epoch 2 over a working set larger than the RAM cache serves
        RAM + spill with spill_hit_bytes > 0 and cache_miss_bytes = 0 —
        the SOURCE is never re-read. With ``spill_engine_io`` the spill
        serves themselves ride the engine (every warm-epoch engine byte is
        spill traffic, none source); with the legacy route the engine sees
        nothing at all."""
        ctx = StromContext(StromConfig(
            engine="python", queue_depth=8, num_buffers=16,
            slab_pool_bytes=32 * MiB, hot_cache_bytes=256 * KiB,
            hot_cache_admit="always", spill_bytes=16 * MiB,
            spill_dir=str(tmp_path), spill_engine_io=engine_io))
        try:
            p = str(tmp_path / "src.bin")
            data = rng.integers(0, 256, 4 * MiB, dtype=np.uint8)
            data.tofile(p)
            step = 256 * KiB
            for off in range(0, len(data), step):
                ctx.pread(p, offset=off, length=step)
            s1 = ctx.stats(sections=["cache", "spill"])
            assert s1["spill"]["spill_spilled_bytes"] > 0
            miss1 = s1["cache"]["cache_miss_bytes"]
            hit1 = s1["spill"]["spill_hit_bytes"]
            eng1 = ctx.engine.stats().get("bytes_read", 0)
            for off in range(0, len(data), step):
                back = ctx.pread(p, offset=off, length=step)
                np.testing.assert_array_equal(back, data[off: off + step])
            s2 = ctx.stats(sections=["cache", "spill"])
            assert s2["spill"]["spill_hit_bytes"] > 0
            assert s2["cache"]["cache_miss_bytes"] == miss1
            eng_delta = ctx.engine.stats().get("bytes_read", 0) - eng1
            spill_served = s2["spill"]["spill_hit_bytes"] - hit1
            if engine_io:
                # spill reads ride the engine now; anything beyond the
                # engine-routed spill serves would be a source re-read
                assert s2["spill"]["spill_engine_ops"] > 0
                assert eng_delta <= spill_served
            else:
                assert s2["spill"]["spill_engine_ops"] == 0
                assert eng_delta == 0
        finally:
            ctx.close()
        # the spill file is unlinked with the context
        assert not any(n.startswith("strom-spill")
                       for n in os.listdir(str(tmp_path)))

    @pytest.mark.parametrize("engine_io", [False, True])
    def test_readahead_promotes_spill_hits_to_ram(self, tmp_path, rng,
                                                  engine_io):
        """ISSUE 14 satellite (ROADMAP item 2 residual c): the warm path
        (ctx.warm — what the Readahead thread drives) probes the spill
        tier and PROMOTES upcoming-window hits back to RAM instead of
        skipping them; the counter proves it and a demand read afterwards
        serves from RAM (no new spill serve, no source read)."""
        from strom.delivery.shard import Segment

        ctx = StromContext(StromConfig(
            engine="python", queue_depth=8, num_buffers=16,
            slab_pool_bytes=32 * MiB, hot_cache_bytes=8 * MiB,
            hot_cache_admit="always", spill_bytes=16 * MiB,
            spill_dir=str(tmp_path), spill_engine_io=engine_io))
        try:
            p = str(tmp_path / "src.bin")
            data = rng.integers(0, 256, 512 * KiB, dtype=np.uint8)
            data.tofile(p)
            n = 128 * KiB
            # spill-seed directly (the deterministic route: eviction
            # timing under slab size-classes is not the point here)
            ctx.hot_cache.spill.offer(p, 0, n, data[:n])
            assert ctx.spill_tier.entries == 1
            promote0 = ctx.spill_tier.stats()["spill_promote_bytes"]
            warmed = ctx.warm(p, [Segment(0, 0, n)])
            st = ctx.spill_tier.stats()
            assert st["spill_promote_bytes"] - promote0 == n
            assert warmed >= 0
            # promoted = RAM-resident now: a demand read is a pure RAM hit
            hit0 = ctx.hot_cache.stats()["cache_hit_bytes"]
            back = ctx.pread(p, offset=0, length=n)
            np.testing.assert_array_equal(back, data[:n])
            assert ctx.hot_cache.stats()["cache_hit_bytes"] - hit0 == n
            # a second warm pass finds it in RAM: no re-promotion
            ctx.warm(p, [Segment(0, 0, n)])
            assert ctx.spill_tier.stats()["spill_promote_bytes"] \
                - promote0 == n
        finally:
            ctx.close()

    def test_spill_off_behavior_unchanged(self, tmp_path, rng):
        """spill_bytes=0 (the default): eviction drops, repeat traffic
        re-reads the source — the pre-spill contract, bit-identical."""
        ctx = StromContext(StromConfig(
            engine="python", queue_depth=8, num_buffers=16,
            slab_pool_bytes=32 * MiB, hot_cache_bytes=256 * KiB,
            hot_cache_admit="always"))
        try:
            assert ctx.spill_tier is None
            p = str(tmp_path / "src.bin")
            data = rng.integers(0, 256, 2 * MiB, dtype=np.uint8)
            data.tofile(p)
            for _ in range(2):
                for off in range(0, len(data), 256 * KiB):
                    back = ctx.pread(p, offset=off, length=256 * KiB)
                    np.testing.assert_array_equal(
                        back, data[off: off + 256 * KiB])
            assert ctx.stats(sections=["cache"])["cache"][
                "cache_miss_bytes"] > 0
        finally:
            ctx.close()

    def test_registered_tenant_carves_spill_partition(self, tmp_path):
        ctx = StromContext(StromConfig(
            engine="python", queue_depth=8, num_buffers=16,
            slab_pool_bytes=32 * MiB, hot_cache_bytes=1 * MiB,
            spill_bytes=8 * MiB, spill_dir=str(tmp_path)))
        try:
            ctx.register_tenant("t1", hot_cache_bytes=512 * KiB)
            assert "t1" in ctx.spill_tier.partitions()
        finally:
            ctx.close()


class TestWriteInvalidation:
    def test_invalidate_sweeps_derived_tuple_keys(self, tmp_path, rng):
        """Decoded-frame entries key on ('jpegdec', path, lo, hi, fp)
        tuples: invalidating the path must drop them (RAM and spill) —
        pixels decoded from overwritten bytes may not survive."""
        cache = HotCache(8 * MiB, admit="always")
        cache.spill = SpillTier(str(tmp_path / "sp.bin"), 8 * MiB)
        raw = rng.integers(0, 256, 4 * KiB, dtype=np.uint8)
        dec = rng.integers(0, 256, 8 * KiB, dtype=np.uint8)
        cache.admit("/data/shard.tar", 0, len(raw), raw)
        dkey = ("jpegdec", "/data/shard.tar", 0, 4096, "rgb8/cv2")
        cache.admit(dkey, 0, len(dec), dec)
        cache.spill.offer(dkey, 0, len(dec), dec)
        assert cache.invalidate("/data/shard.tar") == 2
        assert cache.view("/data/shard.tar", 0, len(raw)) is None
        assert cache.view(dkey, 0, len(dec)) is None
        _, sp_miss = cache.spill.lookup(dkey, 0, len(dec))
        assert sp_miss
        cache.spill.close()

    def test_pwrite_then_read_serves_new_bytes(self, tmp_path, rng):
        """A cached-then-overwritten file serves the NEW bytes: pwrite
        invalidates AFTER the write lands (a pre-write invalidation would
        leave bytes re-admitted mid-window stale forever)."""
        ctx = StromContext(StromConfig(
            engine="python", queue_depth=8, num_buffers=16,
            slab_pool_bytes=32 * MiB, hot_cache_bytes=8 * MiB,
            hot_cache_admit="always", spill_bytes=8 * MiB,
            spill_dir=str(tmp_path)))
        try:
            p = str(tmp_path / "f.bin")
            v1 = rng.integers(0, 256, 256 * KiB, dtype=np.uint8)
            v1.tofile(p)
            np.testing.assert_array_equal(ctx.pread(p), v1)  # cached
            np.testing.assert_array_equal(ctx.pread(p), v1)  # from RAM
            v2 = rng.integers(0, 256, 256 * KiB, dtype=np.uint8)
            ctx.pwrite(p, v2, fsync=True)
            np.testing.assert_array_equal(ctx.pread(p), v2)
        finally:
            ctx.close()
