"""Multi-host integration: real processes, jax.distributed over localhost,
8 global devices (SURVEY.md §4.2 'Multi-host' row; §2.3 coordination duties).
Verifies per-host shard-local delivery, a cross-process sharded train step,
epoch-boundary barriers, and straggler accounting — at both 2 and 4
processes (VERDICT.md next-round #6).

Unit tests for the coordination primitives themselves (balanced assignment,
straggler stats) live here too; they need no subprocesses.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from strom.parallel.multihost import StragglerMonitor, assign_balanced


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize("nproc,ndev", [(2, 4), (4, 2)],
                         ids=["2proc-4dev", "4proc-2dev"])
def test_multiprocess_delivery_train_coordination(tmp_path, nproc, ndev):
    rng = np.random.default_rng(42)
    for i in range(2):
        # ids < LlamaConfig.tiny().vocab so batches feed the train step
        rng.integers(0, 500, 17 * 40 + 3, dtype=np.int32).tofile(
            tmp_path / f"shard{i}.bin")
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", "multihost_worker.py"),
             str(pid), str(nproc), str(port), str(tmp_path), str(ndev)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo, env=env)
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            # 900s, not 420: the 4-proc case takes ~390s ALONE on this
            # 1-core box, and suite-internal load (engine rebuilds, jax
            # compiles in neighboring tests) pushed it past 420 (observed)
            out, _ = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"worker {pid}: delivery ok ({ndev} local shards)" in out, \
            out[-2000:]
        assert f"worker {pid}: train ok" in out, out[-2000:]
        assert f"worker {pid}: coordination ok" in out, out[-2000:]
    # replicated loss must agree bit-for-bit across processes
    losses = {o.split("loss=")[1].split()[0].strip() for o in outs}
    assert len(losses) == 1, losses


# -- coordination primitives (no subprocess needed) --------------------------

def test_assign_balanced_skewed_sizes():
    # Skewed row-group fixture: one giant unit + many small ones. Round-robin
    # by index would put the giant and ~half the rest on host 0; LPT must not.
    sizes = [1000] + [10] * 19
    bins = assign_balanced(sizes, 4)
    loads = [sum(sizes[i] for i in b) for b in bins]
    # every unit assigned exactly once
    assert sorted(i for b in bins for i in b) == list(range(20))
    # the giant unit sits alone; the small ones spread over the other bins
    giant_bin = next(b for b in bins if 0 in b)
    assert giant_bin == [0]
    others = [ld for b, ld in zip(bins, loads) if 0 not in b]
    assert max(others) - min(others) <= 10  # within one small unit
    # makespan: the giant unit alone is the optimum, and LPT achieves it here
    assert max(loads) == 1000


def test_assign_balanced_deterministic_and_ordered():
    sizes = [7, 3, 9, 1, 5, 5, 2, 8]
    a = assign_balanced(sizes, 3)
    b = assign_balanced(sizes, 3)
    assert a == b  # same on every "process" with no coordination
    for bin_ in a:
        assert bin_ == sorted(bin_)  # deterministic iteration within a host


def test_assign_balanced_more_bins_than_units():
    bins = assign_balanced([5, 3], 4)
    assert sorted(i for b in bins for i in b) == [0, 1]
    assert sum(1 for b in bins if b) == 2


def test_assign_balanced_rejects_bad_bins():
    with pytest.raises(ValueError):
        assign_balanced([1, 2], 0)


def test_assign_balanced_pod_scale():
    """Pod shape (VERDICT.md r3 next #5): 256 bins (v5p-256 hosts), 10,000
    skewed units. The heap-based LPT must stay fast enough to run on every
    process at every scan with no coordination, and the makespan must be
    near-ideal — the balance claim at the scale BASELINE.json:11 names, not
    just at the 8-process integration size."""
    import time

    rng = np.random.default_rng(7)
    # log-normal: heavy-tailed like real compressed column-chunk sizes
    sizes = (np.exp(rng.normal(0, 1.0, 10_000)) * 1e6).astype(np.int64)
    t0 = time.perf_counter()
    bins = assign_balanced([int(s) for s in sizes], 256)
    dt = time.perf_counter() - t0
    assert sorted(i for b in bins for i in b) == list(range(10_000))
    loads = np.array([sum(int(sizes[i]) for i in b) for b in bins])
    ideal = sizes.sum() / 256
    # LPT guarantees 4/3 OPT; with 10k units over 256 bins it is far tighter
    assert loads.max() / ideal < 1.01, loads.max() / ideal
    # runtime bound: a second on a 1-core CI box, milliseconds on real hosts
    # (measured 25ms here; the pre-heap O(n*b) scan measured 466ms)
    assert dt < 1.0, f"assign_balanced took {dt:.2f}s at pod scale"


def test_assign_balanced_heap_matches_naive():
    """The heap LPT (O(n log b)) must produce EXACTLY the assignment of the
    reference lightest-bin scan it replaced — determinism across processes
    is load-bearing (every process computes its own copy)."""
    rng = np.random.default_rng(3)
    sizes = [int(s) for s in rng.integers(1, 10_000, 500)]
    n_bins = 13

    def naive(sizes, n_bins):
        order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
        loads = [0] * n_bins
        bins = [[] for _ in range(n_bins)]
        for i in order:
            b = min(range(n_bins), key=lambda j: (loads[j], j))
            bins[b].append(i)
            loads[b] += sizes[i]
        return [sorted(b) for b in bins]

    assert assign_balanced(sizes, n_bins) == naive(sizes, n_bins)


def test_mesh_reducer_cache_reused_across_scans():
    """Repeated scans share ONE jitted all-reduce per mesh: equal meshes
    must hit the reducer cache (a per-scan recompile at v5p-256 would put
    an XLA compile on every scan's critical path — VERDICT.md r3 next #5)."""
    import jax

    from strom.pipelines.parquet_scan import _mesh_reducer, _reducer_cache

    devs = np.asarray(jax.devices())
    m1 = jax.sharding.Mesh(devs, ("scan",))
    m2 = jax.sharding.Mesh(devs, ("scan",))  # fresh but equal object
    assert m1 == m2 and hash(m1) == hash(m2)
    before = len(_reducer_cache)
    f1 = _mesh_reducer(m1)
    f2 = _mesh_reducer(m2)
    assert f1 is f2
    assert len(_reducer_cache) <= before + 1
    # and the cached reducer is actually correct
    out = np.asarray(f1(np.arange(8, dtype=np.int32)[:, None]))
    assert out.ravel().tolist() == [28]


def test_repeated_scans_share_reducer(tmp_path):
    """Two parquet_count_where calls over the same devices add at most one
    reducer-cache entry total (the second scan reuses the first's)."""
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    from strom.config import StromConfig
    from strom.delivery.core import StromContext
    from strom.pipelines.parquet_scan import _reducer_cache, parquet_count_where

    values = np.random.default_rng(5).standard_normal(2_000)
    path = str(tmp_path / "cache.parquet")
    pq.write_table(pa.table({"value": values}), path, row_group_size=500)
    ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                   num_buffers=8))
    try:
        truth = int((values > 0).sum())
        n0 = len(_reducer_cache)
        assert parquet_count_where(ctx, [path], "value",
                                   lambda v: v > 0) == truth
        n1 = len(_reducer_cache)
        assert parquet_count_where(ctx, [path], "value",
                                   lambda v: v > 0) == truth
        assert len(_reducer_cache) == n1  # second scan added nothing
        assert n1 <= n0 + 1
    finally:
        ctx.close()


def test_straggler_monitor_single_process():
    m = StragglerMonitor()
    for t in (0.01, 0.02, 0.03):
        m.record(t)
    steps, mean, p99 = m.local_stats()
    assert steps == 3
    assert mean == pytest.approx(0.02)
    assert p99 == pytest.approx(0.03)
    rep = m.report()
    assert len(rep.hosts) == 1
    assert rep.hosts[0].steps == 3
    assert rep.stragglers == ()
    assert "p0" in str(rep)


def test_straggler_monitor_context_manager():
    import time

    m = StragglerMonitor()
    with m.step():
        time.sleep(0.005)
    steps, mean, _ = m.local_stats()
    assert steps == 1
    assert mean >= 0.004


def test_straggler_monitor_empty():
    m = StragglerMonitor()
    assert m.local_stats() == (0, 0.0, 0.0)
    rep = m.report()
    assert rep.hosts[0].steps == 0
    assert rep.stragglers == ()


def test_parquet_scan_uses_balanced_assignment(tmp_path):
    # Build two parquet files with very different row-group sizes and check
    # that the per-process unit split balances bytes, not counts.
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    from strom.formats.parquet import ParquetShard
    from strom.pipelines.parquet_scan import scan_units

    big = pa.table({"x": np.arange(50_000, dtype=np.int64)})
    small = pa.table({"x": np.arange(100, dtype=np.int64)})
    pq.write_table(big, tmp_path / "big.parquet", row_group_size=50_000)
    pq.write_table(small, tmp_path / "small.parquet", row_group_size=25)
    shards = [ParquetShard(str(tmp_path / "big.parquet")),
              ParquetShard(str(tmp_path / "small.parquet"))]
    units = scan_units(shards)
    sizes = [s.column_chunk_extents(g, ["x"]).size for (s, g) in units]
    bins = assign_balanced(sizes, 2)
    loads = [sum(sizes[i] for i in b) for b in bins]
    # the big row group dominates; it must sit alone in its bin while all
    # four small groups share the other — round-robin would split 1big+2small
    # vs 2small
    big_idx = int(np.argmax(sizes))
    big_bin = next(b for b in bins if big_idx in b)
    assert big_bin == [big_idx]
    assert max(loads) == sizes[big_idx]


@pytest.mark.slow
@pytest.mark.parametrize("nproc", [8, 16], ids=["8proc", "16proc"])
def test_multiproc_parquet_scan_fanout(tmp_path, nproc):
    """8 and 16 single-device processes scan one Parquet file: LPT unit
    assignment covers every row group exactly once, and both reductions
    (the XLA-collective scan-mesh sum and the allgather fallback) agree
    with the locally-computed truth on every process. Scan-only — no TPU,
    CPU mesh over localhost DCN (VERDICT.md r2 missing #4; the 16-process
    arm is r3 next #5's scale step past the 8-process ceiling)."""
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    values = rng.standard_normal(40_000)
    truth = int((values > 0).sum())
    path = str(tmp_path / "scan.parquet")
    # 2 row groups per process so LPT has something to balance everywhere
    pq.write_table(pa.table({"value": values}), path,
                   row_group_size=40_000 // (2 * nproc))

    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable,
             os.path.join(repo, "tests", "parquet_scan_worker.py"),
             str(pid), str(nproc), str(port), path],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo, env=env)
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            # 16 interpreters time-slice one core on this box: scale the
            # budget with the process count (8-proc measured well under 420)
            out, _ = p.communicate(timeout=420 if nproc <= 8 else 840)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"worker {pid}: scan[collective] hits={truth}" in out, \
            out[-2000:]
        assert f"worker {pid}: scan[allgather] hits={truth}" in out, \
            out[-2000:]
        assert f"worker {pid}: scan fanout ok" in out
