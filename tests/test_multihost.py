"""Multi-host integration: 2 real processes, jax.distributed over localhost,
8 global devices (SURVEY.md §4.2 'Multi-host' row). Verifies per-host
shard-local delivery and a cross-process sharded train step."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_delivery_and_train(tmp_path):
    rng = np.random.default_rng(42)
    for i in range(2):
        # ids < LlamaConfig.tiny().vocab so batches feed the train step
        rng.integers(0, 500, 17 * 40 + 3, dtype=np.int32).tofile(
            tmp_path / f"shard{i}.bin")
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", "multihost_worker.py"),
             str(pid), "2", str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo, env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"worker {pid}: delivery ok (4 local shards)" in out, out[-2000:]
        assert f"worker {pid}: train ok" in out, out[-2000:]
    # replicated loss must agree bit-for-bit across processes
    losses = {o.split("loss=")[1].split()[0].strip() for o in outs}
    assert len(losses) == 1, losses
