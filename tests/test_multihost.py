"""Multi-host integration: real processes, jax.distributed over localhost,
8 global devices (SURVEY.md §4.2 'Multi-host' row; §2.3 coordination duties).
Verifies per-host shard-local delivery, a cross-process sharded train step,
epoch-boundary barriers, and straggler accounting — at both 2 and 4
processes (VERDICT.md next-round #6).

Unit tests for the coordination primitives themselves (balanced assignment,
straggler stats) live here too; they need no subprocesses.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from strom.parallel.multihost import StragglerMonitor, assign_balanced


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize("nproc,ndev", [(2, 4), (4, 2)],
                         ids=["2proc-4dev", "4proc-2dev"])
def test_multiprocess_delivery_train_coordination(tmp_path, nproc, ndev):
    rng = np.random.default_rng(42)
    for i in range(2):
        # ids < LlamaConfig.tiny().vocab so batches feed the train step
        rng.integers(0, 500, 17 * 40 + 3, dtype=np.int32).tofile(
            tmp_path / f"shard{i}.bin")
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", "multihost_worker.py"),
             str(pid), str(nproc), str(port), str(tmp_path), str(ndev)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo, env=env)
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            # 900s, not 420: the 4-proc case takes ~390s ALONE on this
            # 1-core box, and suite-internal load (engine rebuilds, jax
            # compiles in neighboring tests) pushed it past 420 (observed)
            out, _ = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"worker {pid}: delivery ok ({ndev} local shards)" in out, \
            out[-2000:]
        assert f"worker {pid}: train ok" in out, out[-2000:]
        assert f"worker {pid}: coordination ok" in out, out[-2000:]
    # replicated loss must agree bit-for-bit across processes
    losses = {o.split("loss=")[1].split()[0].strip() for o in outs}
    assert len(losses) == 1, losses


# -- coordination primitives (no subprocess needed) --------------------------

def test_assign_balanced_skewed_sizes():
    # Skewed row-group fixture: one giant unit + many small ones. Round-robin
    # by index would put the giant and ~half the rest on host 0; LPT must not.
    sizes = [1000] + [10] * 19
    bins = assign_balanced(sizes, 4)
    loads = [sum(sizes[i] for i in b) for b in bins]
    # every unit assigned exactly once
    assert sorted(i for b in bins for i in b) == list(range(20))
    # the giant unit sits alone; the small ones spread over the other bins
    giant_bin = next(b for b in bins if 0 in b)
    assert giant_bin == [0]
    others = [ld for b, ld in zip(bins, loads) if 0 not in b]
    assert max(others) - min(others) <= 10  # within one small unit
    # makespan: the giant unit alone is the optimum, and LPT achieves it here
    assert max(loads) == 1000


def test_assign_balanced_deterministic_and_ordered():
    sizes = [7, 3, 9, 1, 5, 5, 2, 8]
    a = assign_balanced(sizes, 3)
    b = assign_balanced(sizes, 3)
    assert a == b  # same on every "process" with no coordination
    for bin_ in a:
        assert bin_ == sorted(bin_)  # deterministic iteration within a host


def test_assign_balanced_more_bins_than_units():
    bins = assign_balanced([5, 3], 4)
    assert sorted(i for b in bins for i in b) == [0, 1]
    assert sum(1 for b in bins if b) == 2


def test_assign_balanced_rejects_bad_bins():
    with pytest.raises(ValueError):
        assign_balanced([1, 2], 0)


def test_straggler_monitor_single_process():
    m = StragglerMonitor()
    for t in (0.01, 0.02, 0.03):
        m.record(t)
    steps, mean, p99 = m.local_stats()
    assert steps == 3
    assert mean == pytest.approx(0.02)
    assert p99 == pytest.approx(0.03)
    rep = m.report()
    assert len(rep.hosts) == 1
    assert rep.hosts[0].steps == 3
    assert rep.stragglers == ()
    assert "p0" in str(rep)


def test_straggler_monitor_context_manager():
    import time

    m = StragglerMonitor()
    with m.step():
        time.sleep(0.005)
    steps, mean, _ = m.local_stats()
    assert steps == 1
    assert mean >= 0.004


def test_straggler_monitor_empty():
    m = StragglerMonitor()
    assert m.local_stats() == (0, 0.0, 0.0)
    rep = m.report()
    assert rep.hosts[0].steps == 0
    assert rep.stragglers == ()


def test_parquet_scan_uses_balanced_assignment(tmp_path):
    # Build two parquet files with very different row-group sizes and check
    # that the per-process unit split balances bytes, not counts.
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    from strom.formats.parquet import ParquetShard
    from strom.pipelines.parquet_scan import scan_units

    big = pa.table({"x": np.arange(50_000, dtype=np.int64)})
    small = pa.table({"x": np.arange(100, dtype=np.int64)})
    pq.write_table(big, tmp_path / "big.parquet", row_group_size=50_000)
    pq.write_table(small, tmp_path / "small.parquet", row_group_size=25)
    shards = [ParquetShard(str(tmp_path / "big.parquet")),
              ParquetShard(str(tmp_path / "small.parquet"))]
    units = scan_units(shards)
    sizes = [s.column_chunk_extents(g, ["x"]).size for (s, g) in units]
    bins = assign_balanced(sizes, 2)
    loads = [sum(sizes[i] for i in b) for b in bins]
    # the big row group dominates; it must sit alone in its bin while all
    # four small groups share the other — round-robin would split 1big+2small
    # vs 2small
    big_idx = int(np.argmax(sizes))
    big_bin = next(b for b in bins if big_idx in b)
    assert big_bin == [big_idx]
    assert max(loads) == sizes[big_idx]


@pytest.mark.slow
def test_8proc_parquet_scan_fanout(tmp_path):
    """8 single-device processes scan one Parquet file: LPT unit assignment
    covers every row group exactly once, and both reductions (the XLA
    -collective scan-mesh sum and the allgather fallback) agree with the
    locally-computed truth on every process. Scan-only — no TPU, CPU mesh
    over localhost DCN (VERDICT.md r2 missing #4 / next #7)."""
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    values = rng.standard_normal(40_000)
    truth = int((values > 0).sum())
    path = str(tmp_path / "scan.parquet")
    pq.write_table(pa.table({"value": values}), path,
                   row_group_size=40_000 // 16)

    nproc = 8
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable,
             os.path.join(repo, "tests", "parquet_scan_worker.py"),
             str(pid), str(nproc), str(port), path],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo, env=env)
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"worker {pid}: scan[collective] hits={truth}" in out, \
            out[-2000:]
        assert f"worker {pid}: scan[allgather] hits={truth}" in out, \
            out[-2000:]
        assert f"worker {pid}: scan fanout ok" in out
