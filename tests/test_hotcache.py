"""Hot-set host cache + epoch-aware readahead (ISSUE 4 tentpole):
hit/miss/partial-hit split parity (cache-on and cache-off reads are
bit-identical), eviction under byte pressure, refcounts protecting in-flight
readers/puts, second-touch admission, readahead that never issues a
demand-blocking read, and thread safety under a concurrent prefetcher."""

import json
import threading
import time

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.delivery.extents import ExtentList
from strom.delivery.hotcache import CACHE_BENCH_FIELDS, HotCache, Readahead
from strom.delivery.shard import Segment

KiB = 1024
MiB = 1024 * KiB


def _cfg(**kw) -> StromConfig:
    kw.setdefault("engine", "python")
    kw.setdefault("queue_depth", 8)
    kw.setdefault("num_buffers", 16)
    return StromConfig(**kw)


@pytest.fixture()
def ctx_on(data_file):
    c = StromContext(_cfg(hot_cache_bytes=16 * MiB, hot_cache_admit="always"))
    yield c
    c.close()


@pytest.fixture()
def ctx_off():
    c = StromContext(_cfg())
    yield c
    c.close()


class TestHotCacheUnit:
    """The LRU itself: interval hits, budget eviction, refcount lifetimes,
    second-touch — no engine involved."""

    @staticmethod
    def _bytes(n, seed=0):
        return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)

    def test_admit_lookup_roundtrip(self):
        hc = HotCache(4 * MiB, admit="always")
        data = self._bytes(1 * MiB)
        assert hc.admit("f", 0, 1 * MiB, data) == 1 * MiB
        hits, misses, pins = hc.lookup("f", 0, 1 * MiB)
        assert misses == []
        assert len(hits) == 1
        lo, hi, view = hits[0]
        assert (lo, hi) == (0, 1 * MiB)
        np.testing.assert_array_equal(view, data)
        hc.unpin(pins)

    def test_partial_hit_split(self):
        """An overlapping request splits into exact hit windows and exact
        miss gaps — the ranges the delivery layer serves vs submits."""
        hc = HotCache(4 * MiB, admit="always")
        data = self._bytes(1 * MiB, seed=1)
        hc.admit("f", 4096, 4096 + 1 * MiB, data)
        hits, misses, pins = hc.lookup("f", 0, 2 * MiB)
        assert [(lo, hi) for lo, hi, _ in hits] == [(4096, 4096 + 1 * MiB)]
        assert misses == [(0, 4096), (4096 + 1 * MiB, 2 * MiB)]
        np.testing.assert_array_equal(hits[0][2], data)
        # sub-range of a cached entry is a pure view hit
        hc.unpin(pins)
        hits, misses, pins = hc.lookup("f", 8192, 8192 + 4096)
        assert misses == []
        np.testing.assert_array_equal(hits[0][2], data[4096:8192])
        hc.unpin(pins)

    def test_disjoint_admission_trims_overlap(self):
        """Re-admitting an overlapping range only fills the gaps (entries
        stay disjoint; no double-billing of the budget)."""
        hc = HotCache(8 * MiB, admit="always")
        a = self._bytes(1 * MiB, seed=2)
        hc.admit("f", 0, 1 * MiB, a)
        b = self._bytes(2 * MiB, seed=3)
        admitted = hc.admit("f", 0, 2 * MiB, b)
        assert admitted == 1 * MiB  # only the uncovered second half
        hits, misses, pins = hc.lookup("f", 0, 2 * MiB)
        assert misses == []
        got = np.concatenate([v for _, _, v in hits])
        np.testing.assert_array_equal(got[:1 * MiB], a)       # original kept
        np.testing.assert_array_equal(got[1 * MiB:], b[1 * MiB:])
        hc.unpin(pins)
        assert hc.bytes == 2 * MiB

    def test_eviction_under_byte_pressure(self):
        hc = HotCache(2 * MiB, admit="always")
        for i in range(4):  # 4 x 1MiB through a 2MiB budget
            hc.admit(f"f{i}", 0, 1 * MiB, self._bytes(1 * MiB, seed=i))
        assert hc.bytes <= 2 * MiB
        assert hc.evictions >= 2
        # oldest evicted, newest resident (LRU order)
        assert hc.lookup("f0", 0, 1 * MiB)[1] == [(0, 1 * MiB)]
        hits, misses, pins = hc.lookup("f3", 0, 1 * MiB)
        assert misses == []
        hc.unpin(pins)

    def test_refcount_protects_pinned_entry(self):
        """An entry evicted while pinned keeps its buffer alive (and
        correct) until the LAST unpin — the in-flight put/memcpy can never
        read a recycled slab."""
        hc = HotCache(1 * MiB, admit="always")
        data = self._bytes(1 * MiB, seed=7)
        hc.admit("f", 0, 1 * MiB, data)
        hits, _, pins = hc.lookup("f", 0, 1 * MiB)
        entry = pins[0]
        # budget pressure: the only victim is pinned -> eviction must skip
        # it, the new entry is dropped, the pinned buffer survives
        assert hc.admit("g", 0, 1 * MiB, self._bytes(1 * MiB, seed=8)) == 0
        np.testing.assert_array_equal(hits[0][2], data)
        assert entry.buf is not None
        # explicit clear() also skips pinned entries
        hc.clear()
        np.testing.assert_array_equal(hits[0][2], data)
        hc.unpin(pins)
        # unpinned now: pressure can evict it
        assert hc.admit("g", 0, 1 * MiB,
                        self._bytes(1 * MiB, seed=8)) == 1 * MiB
        assert hc.lookup("f", 0, 1 * MiB)[1] == [(0, 1 * MiB)]

    def test_dead_entry_freed_on_last_unpin(self):
        pool_released = []

        class FakePool:
            def acquire(self, n):
                return np.zeros(n, dtype=np.uint8)

            def release(self, buf):
                pool_released.append(buf.nbytes)

        hc = HotCache(1 * MiB, admit="always", pool=FakePool())
        hc.admit("f", 0, 1 * MiB, self._bytes(1 * MiB))
        _, _, pins = hc.lookup("f", 0, 1 * MiB)
        hc.clear()  # evicted-while-pinned: slab NOT released yet
        assert pool_released == []
        hc.unpin(pins)  # last unpin frees
        assert pool_released == [1 * MiB]

    def test_second_touch_admission(self):
        hc = HotCache(4 * MiB, admit="second_touch")
        data = self._bytes(1 * MiB, seed=9)
        assert hc.admit("f", 0, 1 * MiB, data) == 0       # first touch: observe
        assert hc.admit("f", 0, 1 * MiB, data) == 1 * MiB  # second: admit
        hits, misses, pins = hc.lookup("f", 0, 1 * MiB)
        assert misses == []
        hc.unpin(pins)
        # force=True (the readahead path) bypasses the ledger
        assert hc.admit("g", 0, 4096, self._bytes(4096), force=True) == 4096

    def test_view_full_hit_only(self):
        hc = HotCache(4 * MiB, admit="always")
        data = self._bytes(1 * MiB, seed=11)
        hc.admit("f", 4096, 4096 + 1 * MiB, data)
        assert hc.view("f", 0, 4096 + 1 * MiB) is None  # not fully covered
        got = hc.view("f", 8192, 8192 + 64 * KiB)
        assert got is not None
        view, entry = got
        np.testing.assert_array_equal(view, data[4096: 4096 + 64 * KiB])
        assert entry.refs == 1
        hc.unpin([entry])
        assert entry.refs == 0

    def test_oversized_admission_skipped(self):
        hc = HotCache(1 * MiB, admit="always")
        assert hc.admit("f", 0, 2 * MiB, self._bytes(2 * MiB)) == 0
        assert hc.bytes == 0

    def test_budget_charged_at_slab_size_class(self):
        """The budget bills what the slab ALLOCATOR hands back (size class;
        2MiB-rounded under huge pages), not the logical length — resident
        memory must actually respect hot_cache_bytes."""
        from strom.delivery.buffers import size_class

        hc = HotCache(4 * MiB, admit="always")
        n = 600 * KiB  # off-class: rounds up to 640KiB (128KiB steps)
        hc.admit("f", 0, n, self._bytes(n))
        assert hc.bytes == size_class(n) > n

        class HugePool:
            huge = True

            def acquire(self, k):
                return np.zeros(k, dtype=np.uint8)

            def release(self, buf):
                pass

        hp = HotCache(4 * MiB, admit="always", pool=HugePool())
        hp.admit("f", 0, 128 * KiB, self._bytes(128 * KiB))
        assert hp.bytes == 2 * MiB  # one huge page per entry
        # two huge-charged entries fill the 4MiB budget; the third evicts
        hp.admit("g", 0, 128 * KiB, self._bytes(128 * KiB))
        hp.admit("h", 0, 128 * KiB, self._bytes(128 * KiB))
        assert hp.bytes <= 4 * MiB
        assert hp.evictions >= 1


class TestContextParity:
    """Cache-on vs cache-off delivered bytes are bit-identical across
    repeat/overlapping reads (the acceptance criterion's parity half)."""

    def test_pread_repeat_epochs(self, ctx_on, ctx_off, data_file):
        path, data = data_file
        rng = np.random.default_rng(0)
        windows = [(int(o), int(n)) for o, n in zip(
            rng.integers(0, len(data) - 256 * KiB, 12),
            rng.integers(1, 256 * KiB, 12))]
        for _epoch in range(3):
            for off, n in windows:
                a = np.asarray(memoryview(ctx_on.pread(path, off, n)))
                b = np.asarray(memoryview(ctx_off.pread(path, off, n)))
                np.testing.assert_array_equal(a, b)
                np.testing.assert_array_equal(a, data[off: off + n])
        stats = ctx_on.stats()["cache"]
        assert stats["cache_hit_bytes"] > 0  # epochs 2-3 served from RAM

    def test_partial_hit_request_split(self, ctx_on, data_file):
        """A request overlapping a cached range serves the hit from RAM and
        reads only the miss runs — bytes still exact."""
        path, data = data_file
        ctx_on.pread(path, 0, 1 * MiB)  # admits [0, 1MiB)
        got = ctx_on.pread(path, 512 * KiB, 1 * MiB)  # half hit, half miss
        np.testing.assert_array_equal(
            np.asarray(memoryview(got)),
            data[512 * KiB: 512 * KiB + 1 * MiB])
        s = ctx_on.stats()["cache"]
        assert s["cache_hit_bytes"] >= 512 * KiB
        assert s["cache_miss_bytes"] >= 512 * KiB

    def test_full_hit_skips_engine(self, ctx_on, data_file):
        path, data = data_file
        ctx_on.pread(path, 0, 2 * MiB)
        miss0 = ctx_on.stats()["cache"]["cache_miss_bytes"]
        got = ctx_on.pread(path, 0, 2 * MiB)  # repeat: full hit
        np.testing.assert_array_equal(np.asarray(memoryview(got)),
                                      data[: 2 * MiB])
        assert ctx_on.stats()["cache"]["cache_miss_bytes"] == miss0

    def test_extent_list_parity(self, ctx_on, ctx_off, data_file, tmp_path):
        """ExtentList gathers key the cache on PHYSICAL (path, offset):
        batch-relative logical offsets must still hit across differently
        composed requests."""
        path, data = data_file
        p2 = tmp_path / "second.bin"
        data2 = np.random.default_rng(5).integers(0, 256, 1 * MiB,
                                                  dtype=np.uint8)
        data2.tofile(p2)
        el1 = ExtentList([(path, 0, 256 * KiB), (str(p2), 0, 256 * KiB)])
        # same physical bytes, different logical composition + order
        el2 = ExtentList([(str(p2), 0, 128 * KiB), (path, 64 * KiB, 64 * KiB)])
        golden1 = np.concatenate([data[: 256 * KiB], data2[: 256 * KiB]])
        golden2 = np.concatenate([data2[: 128 * KiB],
                                  data[64 * KiB: 128 * KiB]])
        for _ in range(2):
            for el, golden in ((el1, golden1), (el2, golden2)):
                a = np.asarray(memoryview(ctx_on.pread(el)))
                b = np.asarray(memoryview(ctx_off.pread(el)))
                np.testing.assert_array_equal(a, b)
                np.testing.assert_array_equal(a, golden)
        assert ctx_on.stats()["cache"]["cache_hit_bytes"] > 0

    def test_memcpy_ssd2host_parity(self, ctx_on, ctx_off, data_file):
        path, data = data_file
        for _ in range(2):
            a = ctx_on.memcpy_ssd2host(path, offset=4096, length=1 * MiB)
            b = ctx_off.memcpy_ssd2host(path, offset=4096, length=1 * MiB)
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, data[4096: 4096 + 1 * MiB])


class TestPipelineParity:
    """Cache-on vs cache-off PIPELINE batches are bit-identical across two
    epochs (tier-1 acceptance), on the decode-free loader whose batches are
    pure engine gathers."""

    @pytest.fixture(scope="class")
    def pdec_shard(self, tmp_path_factory):
        td = tmp_path_factory.mktemp("hc_pdec")
        n, size = 24, 16
        raw = np.random.default_rng(3).integers(
            0, 256, (n, size, size, 3), dtype=np.uint8)
        path = str(td / "imgs.pdec")
        raw.tofile(path)
        np.save(path + ".labels.npy",
                np.arange(n, dtype=np.int32) % 7)
        with open(path + ".meta.json", "w") as f:
            json.dump({"image_size": size, "n": n}, f)
        return path, raw

    def test_two_epochs_bit_identical(self, pdec_shard):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from strom.pipelines import make_predecoded_vision_pipeline

        path, raw = pdec_shard
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("dp",))
        sharding = NamedSharding(mesh, P("dp", None, None, None))
        ctx_on = StromContext(_cfg(hot_cache_bytes=8 * MiB,
                                   hot_cache_admit="always",
                                   readahead_window_batches=2))
        ctx_off = StromContext(_cfg())
        try:
            bpe = raw.shape[0] // 8
            def epochs(ctx):
                out = []
                with make_predecoded_vision_pipeline(
                        ctx, [path], batch=8, image_size=16,
                        sharding=sharding, seed=11) as pipe:
                    for _ in range(2 * bpe):
                        imgs, lbls = next(pipe)
                        out.append((np.asarray(imgs), np.asarray(lbls)))
                return out
            on, off = epochs(ctx_on), epochs(ctx_off)
            for (ia, la), (ib, lb) in zip(on, off):
                np.testing.assert_array_equal(ia, ib)
                np.testing.assert_array_equal(la, lb)
            # the warm epoch actually served from the cache
            assert ctx_on.stats()["cache"]["cache_hit_bytes"] > 0
        finally:
            ctx_on.close()
            ctx_off.close()


class TestReadahead:
    def test_warm_yields_to_demand(self, ctx_on, data_file):
        """The readahead path must NEVER issue a demand-blocking read: with
        a demand gather in flight, warm() returns without touching the
        engine and counts the yield."""
        path, _ = data_file
        y0 = ctx_on.stats()["cache"]["cache_readahead_yields"]
        with ctx_on._demand_gate():
            assert ctx_on.warm(path, [Segment(0, 0, 1 * MiB)]) == 0
        s = ctx_on.stats()["cache"]
        assert s["cache_readahead_yields"] == y0 + 1
        assert s["cache_readahead_bytes"] == 0

    def test_warm_skips_cached_and_admits_misses(self, ctx_on, data_file):
        path, data = data_file
        ctx_on.pread(path, 0, 1 * MiB)  # cached (admit=always)
        warmed = ctx_on.warm(path, [Segment(0, 0, 2 * MiB)])
        assert warmed == 1 * MiB  # only the uncached second half read
        miss0 = ctx_on.stats()["cache"]["cache_miss_bytes"]
        got = ctx_on.pread(path, 0, 2 * MiB)  # now a full hit
        np.testing.assert_array_equal(np.asarray(memoryview(got)),
                                      data[: 2 * MiB])
        assert ctx_on.stats()["cache"]["cache_miss_bytes"] == miss0

    def test_readahead_thread_warms_window(self, ctx_on, data_file):
        path, data = data_file
        ra = Readahead(
            ctx_on, lambda: [(path, [Segment(0, 0, 1 * MiB)], 0)],
            interval_s=0.005)
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if ctx_on.stats()["cache"]["cache_readahead_bytes"] >= 1 * MiB:
                    break
                time.sleep(0.01)
            else:
                pytest.fail("readahead never warmed the window")
        finally:
            ra.close()
        miss0 = ctx_on.stats()["cache"]["cache_miss_bytes"]
        got = ctx_on.pread(path, 0, 1 * MiB)
        np.testing.assert_array_equal(np.asarray(memoryview(got)),
                                      data[: 1 * MiB])
        assert ctx_on.stats()["cache"]["cache_miss_bytes"] == miss0

    def test_broken_window_fn_counted_not_silent(self, ctx_on):
        """A window_fn that raises must not kill the thread NOR vanish:
        cache_readahead_errors distinguishes 'broken' from 'nothing to
        warm' (both read as readahead_bytes == 0)."""
        def boom():
            raise RuntimeError("window_fn broke")

        ra = Readahead(ctx_on, boom, interval_s=0.001)
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if ctx_on.stats()["cache"]["cache_readahead_errors"]:
                    break
                time.sleep(0.005)
            else:
                pytest.fail("readahead error never counted")
        finally:
            ra.close()

    def test_disabled_cache_serves_and_warms_nothing(self, ctx_on,
                                                     data_file):
        """The enabled gate (bench phase scoping): a disabled cache is
        bypassed end to end — no serving, no admission, no warming — and
        re-enabling restores it."""
        path, data = data_file
        ctx_on.hot_cache.enabled = False
        got = ctx_on.pread(path, 0, 1 * MiB)
        np.testing.assert_array_equal(np.asarray(memoryview(got)),
                                      data[: 1 * MiB])
        s = ctx_on.stats()["cache"]
        assert s["cache_hit_bytes"] == 0 and s["cache_miss_bytes"] == 0
        assert s["cache_admitted_bytes"] == 0
        assert ctx_on.warm(path, [Segment(0, 0, 1 * MiB)]) == 0
        assert ctx_on.stats()["cache"]["cache_readahead_bytes"] == 0
        ctx_on.hot_cache.enabled = True
        ctx_on.pread(path, 0, 1 * MiB)
        assert ctx_on.stats()["cache"]["cache_admitted_bytes"] == 1 * MiB

    def test_thread_safety_under_concurrent_prefetcher(self, data_file):
        """Demand readers (a prefetcher's worker threads) racing the
        readahead warmer and each other: every delivered byte must stay
        exact while admission/eviction churn underneath."""
        path, data = data_file
        # small budget: eviction churns while readers hold views
        ctx = StromContext(_cfg(hot_cache_bytes=2 * MiB,
                                hot_cache_admit="always",
                                delivery_workers=4))
        errors: list = []

        def reader(seed: int) -> None:
            rng = np.random.default_rng(seed)
            try:
                for _ in range(30):
                    off = int(rng.integers(0, len(data) - 512 * KiB))
                    n = int(rng.integers(1, 512 * KiB))
                    got = np.asarray(memoryview(ctx.pread(path, off, n)))
                    np.testing.assert_array_equal(got, data[off: off + n])
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

        ra = Readahead(
            ctx, lambda: [(path, [Segment(0, 0, 1 * MiB)], 0),
                          (path, [Segment(2 * MiB, 0, 1 * MiB)], 0)],
            interval_s=0.001)
        threads = [threading.Thread(target=reader, args=(i,))
                   for i in range(4)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        finally:
            ra.close()
            ctx.close()
        assert not errors, errors


class TestObsExposure:
    def test_metrics_and_stats_routes_expose_cache(self, ctx_on, data_file):
        """Cache counters ride /metrics (typed per the PR 3 exposition
        rules: HELP + counter/gauge TYPE) and /stats."""
        import urllib.request

        from strom.obs.server import MetricsServer

        path, _ = data_file
        ctx_on.pread(path, 0, 1 * MiB)
        ctx_on.pread(path, 0, 1 * MiB)
        srv = MetricsServer(ctx_on.stats, port=0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
                text = r.read().decode()
            assert "# HELP strom_cache_cache_hit_bytes" in text
            assert "# TYPE strom_cache_cache_hit_bytes counter" in text
            assert "# TYPE strom_cache_cache_hit_ratio gauge" in text
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/stats", timeout=10) as r:
                doc = json.loads(r.read().decode())
            cache = doc["sections"]["cache"]
            assert cache["cache_hit_bytes"] > 0
            assert 0.0 < cache["cache_hit_ratio"] <= 1.0
        finally:
            srv.close()

    def test_cache_spans_on_event_ring(self, data_file):
        from strom.obs.events import ring

        path, _ = data_file
        ctx = StromContext(_cfg(hot_cache_bytes=8 * MiB,
                                hot_cache_admit="always"))
        try:
            t0 = ring.now_us()
            ctx.pread(path, 0, 1 * MiB)
            ctx.pread(path, 0, 1 * MiB)
            names = {e["name"] for e in ring.snapshot()
                     if e.get("cat") == "cache" and e["ts_us"] >= t0}
            assert "cache.admit" in names
            assert "cache.serve" in names
        finally:
            ctx.close()


def test_sampler_peek_is_epoch_aware():
    """peek() exports the upcoming window without moving the cursor and
    crosses the epoch boundary into the next permutation."""
    from strom.pipelines.sampler import EpochShuffleSampler

    s = EpochShuffleSampler(12, 4, seed=3)
    it = iter(s)
    first = next(it)
    # cursor now at batch 1 of epoch 0; peek 4 batches = rest of epoch 0
    # (2 batches) + head of epoch 1 (2 batches)
    window = s.peek(4)
    assert len(window) == 4
    upcoming = [next(it) for _ in range(4)]
    for w, u in zip(window, upcoming):
        np.testing.assert_array_equal(w, u)
    # the epoch-0 permutation covered all records exactly once
    seen = np.sort(np.concatenate([first] + upcoming[:2]))
    np.testing.assert_array_equal(seen, np.arange(12))


def test_cache_bench_fields_match_producer():
    """The driver's per-arm copy loop and compare_rounds consume exactly the
    keys cli._cache_epoch_phases produces (the CACHE_BENCH_FIELDS
    single-source contract — see also tests/test_compare_rounds.py)."""
    import inspect

    from strom.cli import _cache_epoch_phases

    src = inspect.getsource(_cache_epoch_phases)
    for key in CACHE_BENCH_FIELDS:
        assert f'"{key}"' in src, \
            f"CACHE_BENCH_FIELDS names {key!r} but _cache_epoch_phases " \
            "does not produce it"
