"""strom/utils/stats exposition layer (ISSUE 3 satellites): exact _sum
through snapshots, counter-vs-gauge typing, HELP lines, non-dict section
tolerance, delta percentiles, and the bench-key parity contract with
tools/compare_rounds.py (silent renames must fail a test, not a dashboard)."""

import importlib.util
import os

import pytest

from strom.utils.stats import (StatsRegistry, all_counter_names, global_stats,
                               percentile_from_buckets, sections_prometheus)


def _load_compare_rounds():
    spec = importlib.util.spec_from_file_location(
        "compare_rounds",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "compare_rounds.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestExposition:
    def test_snapshot_carries_exact_total_us(self):
        reg = StatsRegistry("t")
        # values whose mean*count reconstruction would lose precision once
        # rounded: the snapshot must carry the exact accumulated sum
        for v in (3.1, 100.7, 0.9, 12345.678):
            reg.observe_us("lat", v)
        snap = reg.snapshot()
        assert snap["lat_total_us"] == pytest.approx(3.1 + 100.7 + 0.9
                                                     + 12345.678)
        # the Prometheus _sum is that exact total, not mean*count
        txt = reg.prometheus()
        sum_line = [l for l in txt.splitlines()
                    if l.startswith("t_lat_us_sum")][0]
        assert float(sum_line.split()[1]) == pytest.approx(snap["lat_total_us"])

    def test_counter_vs_gauge_typing_and_help(self):
        reg = StatsRegistry("t2")
        reg.add("bytes_read", 10)
        reg.set_gauge("depth", 4)
        txt = reg.prometheus()
        assert "# TYPE t2_bytes_read counter" in txt
        assert "# TYPE t2_depth gauge" in txt
        assert "# HELP t2_bytes_read" in txt
        assert "# HELP t2_depth" in txt

    def test_hist_summary_keys_not_duplicated_as_gauges(self):
        """The snapshot's derived p50/mean/count/total keys fold into the
        histogram block instead of doubling as free-standing gauges."""
        reg = StatsRegistry("t3")
        reg.observe_us("lat", 50.0)
        txt = reg.prometheus()
        assert "# TYPE t3_lat_us histogram" in txt
        for stray in ("t3_lat_p50_us", "t3_lat_mean_us", "t3_lat_total_us",
                      "t3_lat_count "):
            assert stray not in txt

    def test_sections_prometheus_skips_non_dict_sections(self):
        txt = sections_prometheus({
            "ok": {"n": 1, "flag": True, "name": "python", "frac": 0.5},
            "weird": "just a string",
            "also_weird": 42,
            "none_section": None,
        })
        assert "strom_ok_n 1" in txt
        assert "strom_ok_flag 1" in txt       # bool -> 0/1 gauge
        assert "strom_ok_frac 0.5" in txt
        assert "python" not in txt            # string leaf skipped
        assert "weird" not in txt             # non-dict sections skipped

    def test_sections_counter_typing_via_registry_mirror(self):
        """Section keys that mirror a registered monotonic counter type as
        counter; unknown keys stay gauges."""
        global_stats.add("parity_mirror_total", 2)
        txt = sections_prometheus({"s": {"parity_mirror_total": 2,
                                         "some_gauge": 1}})
        assert "# TYPE strom_s_parity_mirror_total counter" in txt
        assert "# TYPE strom_s_some_gauge gauge" in txt
        assert "parity_mirror_total" in all_counter_names()

    def test_percentile_from_buckets_on_deltas(self):
        reg = StatsRegistry("t4")
        for _ in range(5):
            reg.observe_us("lat", 10.0)
        snap0 = reg.snapshot()
        for _ in range(4):
            reg.observe_us("lat", 1000.0)
        snap1 = reg.snapshot()
        delta = [a - b for a, b in zip(snap1["lat_hist"], snap0["lat_hist"])]
        # the DELTA window contains only the 1000us observations: its p50 is
        # the 1000us bucket's upper bound, while the cumulative hist's p50
        # would still straddle the early 10us points
        assert percentile_from_buckets(delta, 0.50) == 1024.0
        assert percentile_from_buckets(snap1["lat_hist"], 0.50) < 1024.0
        assert percentile_from_buckets([], 0.5) == 0.0
        assert percentile_from_buckets([0, 0, 0], 0.9) == 0.0

    def test_hist_lines_fallback_without_total(self):
        """Producers that hand-assemble stats dicts (engine aggregations
        predating the exact-sum key) still expose a histogram: _sum falls
        back to mean*count."""
        txt = sections_prometheus({"e": {
            "read_latency_hist": [0, 2, 0], "read_latency_mean_us": 3.0,
            "read_latency_count": 2}})
        assert 'e_read_latency_us_bucket{le="+Inf"} 2' in txt
        assert "e_read_latency_us_sum 6.0" in txt


class TestBenchKeyParity:
    """Every stats key tools/compare_rounds.py consumes must be one a bench
    artifact actually produces — a rename on either side fails HERE instead
    of silently blanking a dashboard column (ISSUE 3 satellite)."""

    def test_decode_keys_match_producers(self):
        from strom.cli import _DECODE_COUNTERS

        cr = _load_compare_rounds()
        # keys the vision benches emit per arm (cli.bench_resnet/vit +
        # _decode_stats_delta), which the driver prefixes with the arm name
        produced = set(_DECODE_COUNTERS) | {
            "decode_batch_p50_us", "decode_batch_mean_us",
            "images_per_s", "train_images_per_s"}
        for key in cr.DECODE_KEYS:
            prefix, suffix = key.split("_", 1)
            assert prefix in ("resnet", "vit"), key
            assert suffix in produced, \
                f"compare_rounds consumes {key!r} but no bench produces " \
                f"{suffix!r} (renamed counter?)"

    def test_stall_keys_match_producers(self):
        from strom.obs.stall import STALL_FIELDS

        cr = _load_compare_rounds()
        produced = set(STALL_FIELDS)
        prefixes = ("train", "resnet_predecoded", "vit_predecoded",
                    "resnet", "vit")
        for key in cr.STALL_KEYS:
            suffix = next((key[len(p) + 1:] for p in prefixes
                           if key.startswith(p + "_")), None)
            assert suffix is not None, key
            assert suffix in produced, \
                f"compare_rounds consumes {key!r} but stall attribution " \
                f"produces no {suffix!r} (renamed bucket?)"

    def test_stall_fields_round_trip_through_flatten(self):
        from strom.obs import stall

        flat = stall.flatten_summary(stall.steps_summary([]))
        assert set(flat) == set(stall.STALL_FIELDS)
