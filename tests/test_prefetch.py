"""Overlap/0-stall harness: with prefetch depth >= 2 and consumer slower than
the reader, no step stalls; with a throttled reader and no prefetch, stalls
are counted (SURVEY.md §4.2 'Overlap/0-stall' row)."""

import time

from strom.delivery.prefetch import Prefetcher


def make_thunks(n, read_time):
    def thunk(i):
        def run():
            time.sleep(read_time)
            return i
        return run
    return [thunk(i) for i in range(n)]


def test_prefetch_order_and_completeness():
    pf = Prefetcher(make_thunks(10, 0.001), depth=3)
    assert list(pf) == list(range(10))
    assert pf.steps == 10


def test_zero_stalls_when_compute_dominates():
    # reader: 5ms/batch; consumer: 15ms/step; depth 2 → after warmup the queue
    # is always full. The first batch can't exist before the loop starts, so
    # allow the warmup stall only.
    pf = Prefetcher(make_thunks(8, 0.005), depth=2)
    for _ in pf:
        time.sleep(0.015)
    assert pf.data_stall_steps <= 1
    assert pf.steps == 8


def test_stalls_counted_when_io_bound():
    # reader: 20ms/batch; consumer: 0ms; depth 1 → every step stalls.
    pf = Prefetcher(make_thunks(5, 0.02), depth=1)
    for _ in pf:
        pass
    assert pf.data_stall_steps >= 4
    assert pf.stats.snapshot()["stall_wait_count"] >= 4


def test_deeper_prefetch_hides_jitter():
    # occasional slow batch hidden by depth 4
    def thunk(i):
        def run():
            time.sleep(0.04 if i == 3 else 0.002)
            return i
        return run

    pf = Prefetcher([thunk(i) for i in range(12)], depth=4)
    out = []
    for x in pf:
        time.sleep(0.012)
        out.append(x)
    assert out == list(range(12))
    assert pf.data_stall_steps <= 2


def test_close_cancels():
    pf = Prefetcher(make_thunks(100, 0.01), depth=2)
    next(pf)
    pf.close()


class TestAutoDepth:
    """The feedback controller: grow on stalls, shrink when the queue runs
    fully ready, stay inside [min_depth, max_depth] (the slab-pool bound)."""

    def test_grows_under_stalls(self):
        # reader 15ms/batch, consumer 0ms, start depth 1: every step stalls
        # until depth covers the jitter — the controller must climb
        pf = Prefetcher(make_thunks(30, 0.015), depth=1, auto_depth=True,
                        max_depth=8)
        out = list(pf)
        assert out == list(range(30))
        assert pf.depth > 1
        assert pf.stats.snapshot()["depth_grow"] >= 1
        # every move is on the audit trace
        assert pf.depth_trace[0] == (0, 1)
        assert pf.depth_trace[-1][1] == pf.depth

    def test_respects_max_depth_bound(self):
        pf = Prefetcher(make_thunks(40, 0.01), depth=1, auto_depth=True,
                        max_depth=3)
        for _ in pf:
            pass
        assert pf.depth <= 3
        assert max(d for _, d in pf.depth_trace) <= 3

    def test_shrinks_when_lead_ample(self):
        # reader instant, consumer 5ms/step, start depth 8: the queue runs
        # fully ready every pop — depth must come back down
        pf = Prefetcher(make_thunks(60, 0.0), depth=8, auto_depth=True,
                        min_depth=2, max_depth=8)
        for _ in pf:
            time.sleep(0.005)
        assert pf.depth < 8
        assert pf.depth >= 2
        assert pf.stats.snapshot()["depth_shrink"] >= 1

    def test_min_depth_floor(self):
        pf = Prefetcher(make_thunks(80, 0.0), depth=4, auto_depth=True,
                        min_depth=3, max_depth=8)
        for _ in pf:
            time.sleep(0.003)
        assert pf.depth >= 3

    def test_lead_time_recorded(self):
        pf = Prefetcher(make_thunks(10, 0.0), depth=2, auto_depth=True)
        for _ in pf:
            time.sleep(0.004)
        snap = pf.stats.snapshot()
        assert snap.get("lead_count", 0) >= 1
        assert snap["prefetch_depth"] == pf.depth

    def test_fixed_depth_never_moves(self):
        pf = Prefetcher(make_thunks(20, 0.01), depth=2)  # auto off
        for _ in pf:
            pass
        assert pf.depth == 2
        snap = pf.stats.snapshot()
        assert snap.get("depth_grow", 0) == 0
        assert snap.get("depth_shrink", 0) == 0

    def test_order_preserved_while_depth_moves(self):
        # jittery reader + pacing consumer: depth moves both ways, order
        # and completeness must not
        def thunk(i):
            def run():
                time.sleep(0.03 if i % 7 == 3 else 0.001)
                return i
            return run

        pf = Prefetcher([thunk(i) for i in range(50)], depth=2,
                        auto_depth=True, max_depth=6)
        out = []
        for x in pf:
            time.sleep(0.004)
            out.append(x)
        assert out == list(range(50))


def test_bound_depth_by_slab_pool():
    from strom.delivery.prefetch import bound_depth

    assert bound_depth(512 << 20, 64 << 20) == 8
    assert bound_depth(512 << 20, 1 << 20, cap=16) == 16   # capped
    assert bound_depth(16 << 20, 64 << 20) == 2            # floored
    assert bound_depth(0, 64 << 20) == 32                  # pool off -> cap
    assert bound_depth(512 << 20, 0) == 32                 # unknown batch


def test_bound_depth_reserves_hot_cache_budget():
    """ISSUE 4 satellite: auto-depth growth is sized against the slab pool
    MINUS the hot cache's byte budget — cache entries hold pool slabs for
    the run's lifetime, so depth sized on the full pool would double-commit
    that memory (and conversely, a depth claiming the whole pool would
    starve admission)."""
    from strom.delivery.prefetch import bound_depth

    # half the pool reserved: depth halves
    assert bound_depth(512 << 20, 64 << 20, reserve_bytes=256 << 20) == 4
    # reserve swallows the pool: floor, never an error
    assert bound_depth(512 << 20, 64 << 20, reserve_bytes=512 << 20) == 2
    assert bound_depth(512 << 20, 64 << 20, reserve_bytes=1 << 40,
                       floor=3) == 3
    # no reserve = unchanged legacy behavior
    assert bound_depth(512 << 20, 64 << 20, reserve_bytes=0) == 8
    # pool off: the cap still wins (nothing to reserve from)
    assert bound_depth(0, 64 << 20, reserve_bytes=256 << 20) == 32
