"""Overlap/0-stall harness: with prefetch depth >= 2 and consumer slower than
the reader, no step stalls; with a throttled reader and no prefetch, stalls
are counted (SURVEY.md §4.2 'Overlap/0-stall' row)."""

import time

from strom.delivery.prefetch import Prefetcher


def make_thunks(n, read_time):
    def thunk(i):
        def run():
            time.sleep(read_time)
            return i
        return run
    return [thunk(i) for i in range(n)]


def test_prefetch_order_and_completeness():
    pf = Prefetcher(make_thunks(10, 0.001), depth=3)
    assert list(pf) == list(range(10))
    assert pf.steps == 10


def test_zero_stalls_when_compute_dominates():
    # reader: 5ms/batch; consumer: 15ms/step; depth 2 → after warmup the queue
    # is always full. The first batch can't exist before the loop starts, so
    # allow the warmup stall only.
    pf = Prefetcher(make_thunks(8, 0.005), depth=2)
    for _ in pf:
        time.sleep(0.015)
    assert pf.data_stall_steps <= 1
    assert pf.steps == 8


def test_stalls_counted_when_io_bound():
    # reader: 20ms/batch; consumer: 0ms; depth 1 → every step stalls.
    pf = Prefetcher(make_thunks(5, 0.02), depth=1)
    for _ in pf:
        pass
    assert pf.data_stall_steps >= 4
    assert pf.stats.snapshot()["stall_wait_count"] >= 4


def test_deeper_prefetch_hides_jitter():
    # occasional slow batch hidden by depth 4
    def thunk(i):
        def run():
            time.sleep(0.04 if i == 3 else 0.002)
            return i
        return run

    pf = Prefetcher([thunk(i) for i in range(12)], depth=4)
    out = []
    for x in pf:
        time.sleep(0.012)
        out.append(x)
    assert out == list(range(12))
    assert pf.data_stall_steps <= 2


def test_close_cancels():
    pf = Prefetcher(make_thunks(100, 0.01), depth=2)
    next(pf)
    pf.close()
