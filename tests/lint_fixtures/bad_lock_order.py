"""stromlint fixture: every shape the lock-order pass must flag.

NOT imported by anything — the linter parses it as a file. Uses REAL
hierarchy names so the fixture exercises the real rank table.
"""

import threading

from strom.utils.locks import make_lock


class Bad:
    def __init__(self):
        self._cache_lock = make_lock("cache.meta")
        self._pool_lock = make_lock("slab.pool")
        self._mystery_lock = threading.Lock()  # not declared via make_lock

    def inverted(self):
        # slab pool ranks BEFORE hot cache: acquiring it under the cache
        # lock is the canonical inversion
        with self._cache_lock:
            with self._pool_lock:
                pass

    def undeclared_pair(self):
        with self._mystery_lock:
            with self._cache_lock:
                pass

    def unscoped(self):
        self._cache_lock.acquire()

    def helper_inversion(self):
        with self._cache_lock:
            self._frees_a_slab()

    def _frees_a_slab(self):
        with self._pool_lock:
            pass
