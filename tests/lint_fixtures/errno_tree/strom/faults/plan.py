"""stromlint errno fixture: a fault plan referencing an errno the
resilience tables never classified."""

import errno as _errno

DEFAULT_ERR = _errno.EIO
SNEAKY_ERR = _errno.EOWNERDEAD  # classified by neither table
NAMED_ERR = "ETIMEDOUT"
