"""stromlint errno fixture: the classification tables."""

import errno as _errno

TRANSIENT_ERRNOS = frozenset({_errno.EIO, _errno.ETIMEDOUT})
PERMANENT_ERRNOS = frozenset({_errno.EBADF})
