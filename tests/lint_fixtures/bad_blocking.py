"""stromlint fixture: blocking calls under a held lock."""

import time

from strom.utils.locks import make_lock

_LOCK = make_lock("cache.meta")


def bad(cond, q, fut, engine, tok):
    with _LOCK:
        time.sleep(0.1)
        cond.wait()
        q.get()
        fut.result()
        open("/tmp/x")
        engine.poll(tok)


def fine(cond, q, engine, tok):
    with _LOCK:
        cond.wait(0.05)
        q.get(timeout=1.0)
        engine.poll(tok, 1, 0.5)
