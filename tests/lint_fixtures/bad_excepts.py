"""stromlint fixture: swallowed exceptions."""


def swallow(work):
    try:
        work()
    except Exception:
        pass


def counted(work, stats):
    try:
        work()
    except Exception:
        stats.add("fixture_errors")


def reraised(work):
    try:
        work()
    except Exception:
        raise RuntimeError("wrapped") from None
