"""stromlint fixture: pragma handling — one unexplained (itself a
finding), one justified (suppresses cleanly)."""


def unexplained(work):
    try:
        work()
    except Exception:  # stromlint: ignore[swallowed-exceptions]
        pass


def justified(work):
    try:
        work()
    # stromlint: ignore[swallowed-exceptions] -- fixture: the caller
    # re-runs this work and counts failures itself
    except Exception:
        pass
