"""stromlint fixture: anonymous / unreclaimed threads."""

import threading


def spawn():
    t = threading.Thread(target=print)  # no name, not daemon, never joined
    t.start()
    return t


def good():
    t = threading.Thread(target=print, name="fixture-good", daemon=True)
    t.start()
