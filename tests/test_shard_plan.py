"""Shard planning: NamedSharding → byte segments (SURVEY.md §4.2 Unit row)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from strom.delivery.shard import contiguous_segments, dedupe_plans, plan_sharded_read


def segments_equal_numpy(shape, dtype, index):
    """Golden check: reading the planned segments out of the raw bytes must
    equal numpy's fancy-indexed sub-block."""
    arr = np.arange(np.prod(shape), dtype=dtype).reshape(shape)
    raw = arr.tobytes()
    segs = list(contiguous_segments(shape, np.dtype(dtype).itemsize, index))
    sub = arr[index]
    out = bytearray(sub.nbytes)
    for s in segs:
        out[s.dest_offset:s.dest_offset + s.length] = raw[s.file_offset:s.file_offset + s.length]
    np.testing.assert_array_equal(
        np.frombuffer(bytes(out), dtype=dtype).reshape(sub.shape), sub)
    return segs


@pytest.mark.parametrize("shape,index,max_segs", [
    ((8, 4), (slice(0, 4), slice(None)), 1),       # axis0 shard = 1 contiguous run
    ((8, 4), (slice(2, 6), slice(None)), 1),
    ((8, 4), (slice(None), slice(0, 2)), 8),       # axis1 shard = per-row runs
    ((4, 4, 4), (slice(1, 3), slice(None), slice(None)), 1),
    ((4, 4, 4), (slice(None), slice(1, 3), slice(None)), 4),
    ((4, 4, 4), (slice(0, 2), slice(0, 2), slice(None)), 4),
    ((16,), (slice(4, 12),), 1),
])
def test_contiguous_segments_golden(shape, index, max_segs):
    segs = segments_equal_numpy(shape, np.int32, index)
    assert len(segs) <= max_segs


def test_plan_sharded_read_batch_axis():
    devs = jax.devices()
    assert len(devs) == 8, "conftest must fake 8 CPU devices"
    mesh = Mesh(np.array(devs).reshape(8), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    plans = plan_sharded_read((16, 128), np.float32, sharding)
    assert len(plans) == 8
    for p in plans:
        assert p.local_shape == (2, 128)
        assert len(p.segments) == 1  # batch-axis shard is contiguous
        assert p.nbytes == 2 * 128 * 4
    # all byte ranges disjoint, covering the file exactly
    offs = sorted((p.segments[0].file_offset, p.segments[0].length) for p in plans)
    expect = 0
    for off, ln in offs:
        assert off == expect
        expect = off + ln
    assert expect == 16 * 128 * 4


def test_plan_sharded_read_2d():
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(4, 2), ("dp", "tp"))
    sharding = NamedSharding(mesh, P("dp", "tp"))
    plans = plan_sharded_read((8, 64), np.int8, sharding)
    assert len(plans) == 8
    for p in plans:
        assert p.local_shape == (2, 32)
        assert len(p.segments) == 2  # two rows, half-row each


def test_replicated_shards_deduped():
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(8), ("dp",))
    sharding = NamedSharding(mesh, P(None))  # fully replicated
    plans = plan_sharded_read((4, 4), np.float32, sharding)
    groups = dedupe_plans(plans)
    assert len(groups) == 1  # single read, 8 device_puts
    (segs, group), = groups.items()
    assert len(group) == 8


def test_sequence_dim_sharding():
    """Llama packed-token loaders must accept sequence-axis sharding
    (SURVEY.md §5 'Long-context' row)."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "sp"))
    sharding = NamedSharding(mesh, P("dp", "sp"))
    plans = plan_sharded_read((4, 4096), np.int32, sharding)
    for p in plans:
        assert p.local_shape == (2, 1024)
        assert len(p.segments) == 2
