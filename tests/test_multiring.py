"""Multi-ring engine: per-file ring routing, concurrent-transfer interleave
(SURVEY.md §2.1 "DMA submit engine" per-device queues; VERDICT.md r2
missing #3 / next #5)."""

import threading

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext, StripedFile


def _multi_ctx(rings: int, **cfg_kw) -> StromContext:
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    return StromContext(StromConfig(engine="uring", engine_rings=rings,
                                    **cfg_kw))


def test_make_engine_selects_multi():
    from strom.engine import make_engine
    from strom.engine.multi import MultiRingEngine
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    eng = make_engine(StromConfig(engine="uring", engine_rings=3))
    try:
        assert isinstance(eng, MultiRingEngine)
        assert eng.num_rings == 3
        assert eng.concurrent_gathers
        s = eng.stats()
        assert s["rings"] == 3 and len(s["ring_stats"]) == 3
    finally:
        eng.close()


def test_striped_gather_uses_every_ring(tmp_path, rng):
    """A RAID0 gather over 4 members on a 2-ring engine must submit on BOTH
    rings (member i → ring i mod N), with byte-exact results."""
    from strom.engine.raid0 import stripe_file

    n_mem, chunk = 4, 64 * 1024
    data = rng.integers(0, 256, size=4 * 1024 * 1024, dtype=np.uint8)
    src = tmp_path / "src.bin"
    data.tofile(src)
    members = [str(tmp_path / f"m{i}.bin") for i in range(n_mem)]
    stripe_file(str(src), members, chunk)
    ctx = _multi_ctx(2)
    try:
        sf = StripedFile(tuple(members), chunk)
        got = np.asarray(memoryview(ctx.pread(sf, 0, len(data))))
        np.testing.assert_array_equal(got, data)
        per_ring = ctx.engine.stats()["ring_stats"]
        assert len(per_ring) == 2
        for rs in per_ring:
            assert rs["ops_submitted"] > 0, per_ring
            assert rs["bytes_read"] > 0, per_ring
    finally:
        ctx.close()


def test_single_file_transfers_round_robin(tmp_path, rng):
    """Whole-file gathers rotate rings, so back-to-back independent
    transfers land on different rings."""
    data = rng.integers(0, 256, size=1 * 1024 * 1024, dtype=np.uint8)
    p = tmp_path / "f.bin"
    data.tofile(p)
    ctx = _multi_ctx(2)
    try:
        for _ in range(2):
            got = np.asarray(memoryview(ctx.pread(str(p))))
            np.testing.assert_array_equal(got, data)
        per_ring = ctx.engine.stats()["ring_stats"]
        assert all(rs["bytes_read"] == len(data) for rs in per_ring), per_ring
    finally:
        ctx.close()


def test_concurrent_transfers_interleave(tmp_path, rng):
    """With concurrent_gathers the delivery layer drops its whole-transfer
    lock: N threads reading concurrently stay byte-exact and every ring
    carries traffic."""
    size = 2 * 1024 * 1024
    datas, paths = [], []
    for i in range(4):
        d = rng.integers(0, 256, size=size, dtype=np.uint8)
        p = tmp_path / f"c{i}.bin"
        d.tofile(p)
        datas.append(d)
        paths.append(str(p))
    ctx = _multi_ctx(2)
    try:
        errors: list[BaseException] = []

        def worker(i: int) -> None:
            try:
                for _ in range(3):
                    got = np.asarray(memoryview(ctx.pread(paths[i])))
                    np.testing.assert_array_equal(got, datas[i])
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        per_ring = ctx.engine.stats()["ring_stats"]
        assert all(rs["bytes_read"] > 0 for rs in per_ring), per_ring
        agg = ctx.engine.stats()
        assert agg["bytes_read"] == 4 * 3 * size
        # the latency histogram must survive aggregation (Prometheus export
        # reads these keys; they went blank in an earlier multi-ring draft)
        assert sum(agg["read_latency_hist"]) == agg["read_latency_count"] > 0
        assert agg["read_latency_p99_us"] >= agg["read_latency_p50_us"] > 0
        assert agg["read_latency_mean_us"] > 0
    finally:
        ctx.close()


def test_memcpy_and_unregister_roundtrip(tmp_path, rng):
    """The full delivery path (sharded memcpy_ssd2tpu included) rides the
    multi-ring engine; unregistering a file drops it from every ring."""
    data = rng.integers(0, 256, size=512 * 1024, dtype=np.uint8)
    p = tmp_path / "d.bin"
    data.tofile(p)
    ctx = _multi_ctx(2)
    try:
        arr = ctx.memcpy_ssd2tpu(str(p), length=len(data))
        np.testing.assert_array_equal(np.asarray(arr), data)
        fi = ctx.file_index(str(p))
        ctx.engine.unregister_file(fi)
        assert all(fi not in m for m in ctx.engine._child_fi)
    finally:
        ctx.close()


def test_engine_rings_validation():
    with pytest.raises(ValueError, match="engine_rings"):
        StromConfig(engine_rings=0)
