"""Multi-ring engine: per-file ring routing, concurrent-transfer interleave
(SURVEY.md §2.1 "DMA submit engine" per-device queues; VERDICT.md r2
missing #3 / next #5)."""

import threading

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext, StripedFile


def _multi_ctx(rings: int, **cfg_kw) -> StromContext:
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    return StromContext(StromConfig(engine="uring", engine_rings=rings,
                                    **cfg_kw))


def test_make_engine_selects_multi():
    from strom.engine import make_engine
    from strom.engine.multi import MultiRingEngine
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    eng = make_engine(StromConfig(engine="uring", engine_rings=3))
    try:
        assert isinstance(eng, MultiRingEngine)
        assert eng.num_rings == 3
        assert eng.concurrent_gathers
        s = eng.stats()
        assert s["rings"] == 3 and len(s["ring_stats"]) == 3
    finally:
        eng.close()


def test_striped_gather_uses_every_ring(tmp_path, rng):
    """A RAID0 gather over 4 members on a 2-ring engine must submit on BOTH
    rings (member i → ring i mod N), with byte-exact results."""
    from strom.engine.raid0 import stripe_file

    n_mem, chunk = 4, 64 * 1024
    data = rng.integers(0, 256, size=4 * 1024 * 1024, dtype=np.uint8)
    src = tmp_path / "src.bin"
    data.tofile(src)
    members = [str(tmp_path / f"m{i}.bin") for i in range(n_mem)]
    stripe_file(str(src), members, chunk)
    ctx = _multi_ctx(2)
    try:
        sf = StripedFile(tuple(members), chunk)
        got = np.asarray(memoryview(ctx.pread(sf, 0, len(data))))
        np.testing.assert_array_equal(got, data)
        per_ring = ctx.engine.stats()["ring_stats"]
        assert len(per_ring) == 2
        for rs in per_ring:
            assert rs["ops_submitted"] > 0, per_ring
            assert rs["bytes_read"] > 0, per_ring
    finally:
        ctx.close()


def test_single_file_transfers_round_robin(tmp_path, rng):
    """Whole-file gathers rotate rings, so back-to-back independent
    transfers land on different rings."""
    data = rng.integers(0, 256, size=1 * 1024 * 1024, dtype=np.uint8)
    p = tmp_path / "f.bin"
    data.tofile(p)
    ctx = _multi_ctx(2)
    try:
        for _ in range(2):
            got = np.asarray(memoryview(ctx.pread(str(p))))
            np.testing.assert_array_equal(got, data)
        per_ring = ctx.engine.stats()["ring_stats"]
        assert all(rs["bytes_read"] == len(data) for rs in per_ring), per_ring
    finally:
        ctx.close()


def test_concurrent_transfers_interleave(tmp_path, rng):
    """With concurrent_gathers the delivery layer drops its whole-transfer
    lock: N threads reading concurrently stay byte-exact and every ring
    carries traffic."""
    size = 2 * 1024 * 1024
    datas, paths = [], []
    for i in range(4):
        d = rng.integers(0, 256, size=size, dtype=np.uint8)
        p = tmp_path / f"c{i}.bin"
        d.tofile(p)
        datas.append(d)
        paths.append(str(p))
    ctx = _multi_ctx(2)
    try:
        errors: list[BaseException] = []

        def worker(i: int) -> None:
            try:
                for _ in range(3):
                    got = np.asarray(memoryview(ctx.pread(paths[i])))
                    np.testing.assert_array_equal(got, datas[i])
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        per_ring = ctx.engine.stats()["ring_stats"]
        assert all(rs["bytes_read"] > 0 for rs in per_ring), per_ring
        agg = ctx.engine.stats()
        assert agg["bytes_read"] == 4 * 3 * size
        # the latency histogram must survive aggregation (Prometheus export
        # reads these keys; they went blank in an earlier multi-ring draft)
        assert sum(agg["read_latency_hist"]) == agg["read_latency_count"] > 0
        assert agg["read_latency_p99_us"] >= agg["read_latency_p50_us"] > 0
        assert agg["read_latency_mean_us"] > 0
    finally:
        ctx.close()


def test_memcpy_and_unregister_roundtrip(tmp_path, rng):
    """The full delivery path (sharded memcpy_ssd2tpu included) rides the
    multi-ring engine; unregistering a file drops it from every ring."""
    data = rng.integers(0, 256, size=512 * 1024, dtype=np.uint8)
    p = tmp_path / "d.bin"
    data.tofile(p)
    ctx = _multi_ctx(2)
    try:
        arr = ctx.memcpy_ssd2tpu(str(p), length=len(data))
        np.testing.assert_array_equal(np.asarray(arr), data)
        fi = ctx.file_index(str(p))
        ctx.engine.unregister_file(fi)
        assert all(fi not in m for m in ctx.engine._child_fi)
    finally:
        ctx.close()


def test_engine_rings_validation():
    with pytest.raises(ValueError, match="engine_rings"):
        StromConfig(engine_rings=0)


def _uring_engine(rings: int, **cfg_kw):
    from strom.engine import make_engine
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    return make_engine(StromConfig(engine="uring", engine_rings=rings,
                                   **cfg_kw))


def test_fixed_buf_ratio_covers_registered_reads(tmp_path, rng):
    """Registered-buffer coverage gauge (ISSUE 16 satellite): a gather
    into a REGISTERED dest rides READ_FIXED on every ring, so the
    aggregated ratio reads 1.0 with zero unregistered reads; the same
    gather into a plain array drops the ratio and counts the complement."""
    from strom.delivery.buffers import alloc_aligned

    data = rng.integers(0, 256, size=1024 * 1024, dtype=np.uint8)
    path = tmp_path / "f.bin"
    data.tofile(path)
    eng = _uring_engine(2, residency_hybrid=False)
    try:
        if not eng.stats().get("fixed_buffers"):
            pytest.skip("kernel lacks fixed buffers")
        fi = eng.register_file(str(path), o_direct=True)
        dest = alloc_aligned(len(data))
        assert eng.register_dest(dest) == 0
        got = eng.read_vectored([(fi, 0, 0, len(data))], dest)
        assert got == len(data)
        np.testing.assert_array_equal(dest[:got], data)
        s = eng.stats()
        assert s["engine_fixed_buf_ratio"] == 1.0, s
        assert s["engine_unregistered_reads"] == 0, s
        # unregistered dest: the complement shows up in the gauge pair
        plain = np.empty(len(data), dtype=np.uint8)
        eng.read_vectored([(fi, 0, 0, len(data))], plain)
        s = eng.stats()
        assert s["engine_fixed_buf_ratio"] < 1.0, s
        assert s["engine_unregistered_reads"] > 0, s
    finally:
        eng.close()


def test_fixed_path_covers_interior_views(tmp_path, rng):
    """A gather whose dest is a VIEW into a registered slab (data pointer
    strictly inside the registration) still rides READ_FIXED: the kernel
    bounds-checks the address against the whole registered entry, and the
    engine resolves interior pointers, not just exact slab bases. This is
    the shape delivery produces when the scheduler hands an engine a
    sliced sub-span of a pool slab."""
    from strom.delivery.buffers import alloc_aligned

    n = 256 * 1024
    data = rng.integers(0, 256, size=n, dtype=np.uint8)
    path = tmp_path / "f.bin"
    data.tofile(path)
    eng = _uring_engine(2, residency_hybrid=False)
    try:
        if not eng.stats().get("fixed_buffers"):
            pytest.skip("kernel lacks fixed buffers")
        fi = eng.register_file(str(path), o_direct=True)
        slab = alloc_aligned(n + 16384)
        assert eng.register_dest(slab) == 0
        view = slab[8192:8192 + n]  # 512-aligned interior pointer
        got = eng.read_vectored([(fi, 0, 0, n)], view)
        assert got == n
        np.testing.assert_array_equal(view[:n], data)
        s = eng.stats()
        assert s["engine_fixed_buf_ratio"] == 1.0, s
        assert s["engine_unregistered_reads"] == 0, s
    finally:
        eng.close()


def test_ring_recovery_reregisters_dest_buffers(tmp_path, rng):
    """Quarantine recovery replays buffer registrations (ISSUE 16
    satellite): after a member ring is rebuilt, every live dest slab must
    be registered on the NEW child — without the replay a recovered ring
    silently serves plain READ instead of READ_FIXED."""
    import errno
    import time as _time

    from strom.delivery.buffers import alloc_aligned
    from strom.engine.base import EngineError

    data = rng.integers(0, 256, size=512 * 1024, dtype=np.uint8)
    path = tmp_path / "f.bin"
    data.tofile(path)
    eng = _uring_engine(2, breaker_min_events=2, ring_recovery_s=0.05,
                        residency_hybrid=False)
    try:
        if not eng.stats().get("fixed_buffers"):
            pytest.skip("kernel lacks fixed buffers")
        dest = alloc_aligned(len(data))
        assert eng.register_dest(dest) == 0
        sick = eng._children[0]
        e = EngineError(errno.EIO, "injected")
        eng._note_ring_error(0, e)
        eng._note_ring_error(0, e)
        assert eng.stats()["quarantined_rings"] == [0]
        _time.sleep(0.08)
        eng._maybe_recover_rings()
        s = eng.stats()
        assert s["quarantined_rings"] == [], s
        assert s["ring_recoveries"] == 1, s
        child = eng._children[0]
        assert child is not sick
        # the replay: the rebuilt ring carries the live slab registration
        assert child.stats()["ext_buffers"] == 1, child.stats()
        # and serves it via READ_FIXED, byte-exact
        fi = eng.register_file(str(path), o_direct=True)
        got = eng.read_vectored([(fi, 0, 0, len(data))], dest)
        assert got == len(data)
        np.testing.assert_array_equal(dest[:got], data)
        assert eng.stats()["engine_fixed_buf_ratio"] == 1.0
    finally:
        eng.close()
