"""Worker for the 2-process multi-host integration test (launched by
tests/test_multihost.py). Each process owns 4 virtual CPU devices; the llama
pipeline must deliver a global batch where every process reads only the
bytes backing its addressable devices, and the sharded train step must agree
across processes."""

import os
import sys


def main() -> int:
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    data_dir = sys.argv[4]
    ndev = int(sys.argv[5]) if len(sys.argv) > 5 else 4

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={ndev}"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=nproc, process_id=pid)
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from strom.config import StromConfig
    from strom.delivery.core import StromContext
    from strom.models.llama import LlamaConfig
    from strom.parallel.mesh import make_mesh
    from strom.parallel.train import (init_train_state, make_optimizer,
                                      make_train_step)
    from strom.pipelines import make_llama_pipeline

    n_global = len(jax.devices())
    assert n_global == ndev * nproc, f"expected {ndev*nproc} global devices, got {n_global}"

    paths = sorted(os.path.join(data_dir, f) for f in os.listdir(data_dir)
                   if f.endswith(".bin"))
    golden = np.concatenate([
        np.fromfile(p, dtype=np.int32)[: (os.path.getsize(p) // 4) // 17 * 17]
        .reshape(-1, 17) for p in paths])

    mesh = make_mesh({"dp": n_global}, devices=jax.devices())
    sharding = NamedSharding(mesh, P("dp", None))
    ctx = StromContext(StromConfig(engine="python", queue_depth=8, num_buffers=8))
    B = 2 * n_global

    with make_llama_pipeline(ctx, paths, batch=B, seq_len=16,
                             sharding=sharding, shuffle=False) as pipe:
        batch = next(pipe)
        assert batch.shape == (B, 17)
        # every process holds only its addressable shards; check them all
        checked = 0
        for shard in batch.addressable_shards:
            lo, hi, _ = shard.index[0].indices(B)
            np.testing.assert_array_equal(np.asarray(shard.data),
                                          golden[lo:hi])
            checked += 1
        assert checked == ndev, checked
        print(f"worker {pid}: delivery ok ({checked} local shards)", flush=True)

    # sharded train step across all processes (dp spans processes, tp local)
    tmesh = make_mesh({"dp": nproc, "tp": ndev}, devices=jax.devices())
    cfg = LlamaConfig.tiny()
    opt = make_optimizer()
    state = init_train_state(jax.random.PRNGKey(0), cfg, tmesh, opt)
    step = make_train_step(cfg, tmesh, opt)
    with make_llama_pipeline(ctx, paths, batch=2 * nproc, seq_len=16,
                             sharding=NamedSharding(tmesh, P("dp", None)),
                             seed=3) as pipe:
        for _ in range(2):
            state, metrics = step(state, next(pipe))
    loss = float(metrics["loss"])
    assert np.isfinite(loss)
    assert int(state.step) == 2
    print(f"worker {pid}: train ok loss={loss:.6f}", flush=True)

    def make_sharded_batch(sharding, toks_np):
        """Global array from host tokens: each process device_puts only its
        addressable shards (every process holds the same full numpy)."""
        shape = toks_np.shape
        idx_map = sharding.addressable_devices_indices_map(shape)
        return jax.make_array_from_single_device_arrays(
            shape, sharding,
            [jax.device_put(toks_np[i], d) for d, i in idx_map.items()])

    # ring×flash across processes: the sp axis spans the PROCESS boundary
    # (outer mesh axis), so every ppermute hop in the forward ring AND the
    # custom-vjp backward ring rides the distributed backend, not intra-
    # process device transfers
    smesh = make_mesh({"sp": nproc, "dp": ndev}, devices=jax.devices())
    ssharding = NamedSharding(smesh, P("dp", "sp"))
    state = init_train_state(jax.random.PRNGKey(0), cfg, smesh, opt)
    sstep = make_train_step(cfg, smesh, opt, sp=True, attn="flash")
    B2, S = 2 * ndev, 32
    toks_np = np.random.default_rng(11).integers(0, cfg.vocab, (B2, S),
                                                 dtype=np.int32)
    tokens = make_sharded_batch(ssharding, toks_np)
    state, metrics = sstep(state, tokens)
    sloss = float(metrics["loss"])
    assert np.isfinite(sloss), sloss
    print(f"worker {pid}: ring-flash sp-across-processes ok loss={sloss:.6f}",
          flush=True)

    # zigzag variant over the same cross-process sp axis: its entry/exit
    # relayout bijections and balanced ring must agree with the flash ring
    # (same params, same tokens) across the process boundary
    zstate = init_train_state(jax.random.PRNGKey(0), cfg, smesh, opt)
    zstep = make_train_step(cfg, smesh, opt, sp=True, attn="zigzag")
    _, zmetrics = zstep(zstate, make_sharded_batch(ssharding, toks_np))
    zloss = float(zmetrics["loss"])
    assert abs(zloss - sloss) < 2e-3, (zloss, sloss)
    print(f"worker {pid}: zigzag sp-across-processes ok loss={zloss:.6f}",
          flush=True)

    # pipeline parallelism across processes: pp as the OUTER mesh axis means
    # every activation hop between stages crosses the process boundary —
    # microbatch pipelining over DCN, fed per-process
    from strom.parallel.pipeline import make_pp_train_step

    pmesh = make_mesh({"pp": nproc, "dp": ndev}, devices=jax.devices())
    psharding = NamedSharding(pmesh, P("dp", None))
    # one layer per process-stage: n_layers must divide by pp
    cfg_pp = LlamaConfig(vocab=512, d_model=64, n_layers=nproc, n_heads=4,
                         n_kv_heads=2, d_ff=128, rope_theta=10_000.0)
    state = init_train_state(jax.random.PRNGKey(0), cfg_pp, pmesh, opt)
    pstep = make_pp_train_step(cfg_pp, pmesh, opt, microbatches=2)
    B3, S3 = 4 * ndev, 16
    toks3 = np.random.default_rng(12).integers(0, cfg_pp.vocab, (B3, S3),
                                               dtype=np.int32)
    state, metrics = pstep(state, make_sharded_batch(psharding, toks3))
    ploss = float(metrics["loss"])
    # parity, not just finiteness: a stale/garbled cross-process activation
    # hop would still produce a finite loss — compare against the plain
    # dp-only step on the same params/tokens
    dmesh = make_mesh({"dp": n_global}, devices=jax.devices())
    dstate = init_train_state(jax.random.PRNGKey(0), cfg_pp, dmesh, opt)
    dstep = make_train_step(cfg_pp, dmesh, opt)
    _, dref = dstep(dstate, make_sharded_batch(
        NamedSharding(dmesh, P("dp", None)), toks3))
    dloss = float(dref["loss"])
    assert abs(ploss - dloss) < 2e-3, (ploss, dloss)
    print(f"worker {pid}: pipeline-across-processes ok loss={ploss:.6f} "
          f"(dense ref {dloss:.6f})", flush=True)

    # epoch barrier + straggler accounting (SURVEY.md §2.3): consume one
    # full epoch with epoch_sync=True (barrier is collective — a hang here
    # fails the test by timeout), then a collective skew report
    with make_llama_pipeline(ctx, paths, batch=2 * nproc, seq_len=16,
                             sharding=NamedSharding(tmesh, P("dp", None)),
                             seed=5, epoch_sync=True) as pipe:
        bpe = pipe.sampler.batches_per_epoch
        for _ in range(bpe + 1):  # crosses the epoch-0 boundary barrier
            next(pipe)
        rep = pipe.straggler_report()
    assert len(rep.hosts) == nproc, rep
    assert all(h.steps > 0 for h in rep.hosts), rep
    print(f"worker {pid}: coordination ok ({rep})", flush=True)
    ctx.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
