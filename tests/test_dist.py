"""Distributed data plane (ISSUE 15): peer extent service + launcher.

Covers the acceptance invariants directly:

- framing round-trip units (length-prefixed binary, truncation detected),
- an extent hot on host A is served to host B with host B's engine
  ``bytes_read`` delta = 0 (peer hit, no duplicate SSD read), and the
  served range promotes into B's own cache,
- a killed/garbage peer mid-serve degrades to the local engine with
  bit-identical bytes (never fatal),
- per-peer breaker trip + recovery on a fake clock,
- peer-op fault matchers (refused connect / hangup / latency / truncated
  frame) + the ``chaos_net`` preset, isolated from engine read draws,
- subprocess 2- and 4-process runs: global-batch bit-identity vs the
  single-process pipeline and the zero-duplicate-SSD-read invariant,
- the ``stats()["dist"]`` section exposes exactly ``DIST_FIELDS``.
"""

import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.dist.launch import (launch_local, make_fixture, measure_ingest,
                               owner_of, reference_shard_hashes)
from strom.dist.peers import (DIST_BENCH_FIELDS, DIST_FIELDS,
                              PeerProtocolError, PeerTier, decode_request,
                              encode_request, recv_frame, send_frame)
from strom.engine.resilience import CircuitBreaker
from strom.faults.plan import FaultPlan, FaultRule


def _cfg(**kw):
    base = dict(engine="python", queue_depth=8, num_buffers=8,
                hot_cache_bytes=64 << 20, hot_cache_admit="always")
    base.update(kw)
    return StromConfig(**base)


def _fixture(tmp_path, n=256 * 1024, seed=0):
    p = str(tmp_path / "data.bin")
    payload = np.random.default_rng(seed).integers(
        0, 255, n, dtype=np.uint8)
    payload.tofile(p)
    return p, payload


# -- framing units -----------------------------------------------------------

def test_request_roundtrip():
    raw = encode_request("/some/path.bin", 4096, 123456)
    assert decode_request(raw) == ("/some/path.bin", 4096, 123456)


def test_request_rejects_garbage():
    with pytest.raises(PeerProtocolError):
        decode_request(b"\x01\x00")
    with pytest.raises(PeerProtocolError):
        decode_request(encode_request("p", 0, 8) + b"extra")
    # op byte nobody speaks
    bad = bytearray(encode_request("p", 0, 8))
    bad[0] = 99
    with pytest.raises(PeerProtocolError):
        decode_request(bad)


def test_frame_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    try:
        payload = os.urandom(70000)  # > one TCP segment
        t = threading.Thread(target=send_frame, args=(a, payload),
                             name="test-frame-send", daemon=True)
        t.start()
        got = recv_frame(b)
        t.join()
        assert bytes(got) == payload
    finally:
        a.close()
        b.close()


def test_truncated_frame_detected():
    a, b = socket.socketpair()
    try:
        # header promises 100 bytes, sender hangs up after 10
        a.sendall(struct.pack("!I", 100) + b"x" * 10)
        a.close()
        with pytest.raises(PeerProtocolError):
            recv_frame(b)
    finally:
        b.close()


def test_frame_cap_enforced():
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack("!I", 1 << 31))
        with pytest.raises(PeerProtocolError):
            recv_frame(b)
    finally:
        a.close()
        b.close()


# -- peer serve: the zero-duplicate-SSD-read acceptance ----------------------

def test_peer_hit_zero_engine_reads(tmp_path):
    """Extent hot on A, read from B: B's engine bytes_read delta = 0 and
    the bytes are identical; the range then promotes into B's own cache
    (second read = RAM hit, no peer round-trip)."""
    p, payload = _fixture(tmp_path)
    A, B = StromContext(_cfg()), StromContext(_cfg())
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)  # warm A (admit=always)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)

        b0 = B.engine.stats().get("bytes_read", 0)
        got = B.pread(p, 1024, 8192)
        assert bytes(got) == payload[1024:1024 + 8192].tobytes()
        assert B.engine.stats().get("bytes_read", 0) - b0 == 0
        tier = B.peer_tier.stats()
        assert tier["peer_hit_bytes"] == 8192
        # the server tallies a beat after the client has its bytes: poll
        assert _wait_stats(A.peer_server, lambda s: s["peer_serves"] >= 1
                           )["peer_served_bytes"] == 8192

        # promotion: the next read of the same range never leaves B
        hits0 = B.peer_tier.stats()["peer_hits"]
        got2 = B.pread(p, 1024, 8192)
        assert bytes(got2) == bytes(got)
        assert B.engine.stats().get("bytes_read", 0) - b0 == 0
        assert B.peer_tier.stats()["peer_hits"] == hits0
    finally:
        A.close()
        B.close()


def test_peer_miss_falls_back_to_engine(tmp_path):
    """A range the owner does NOT have hot answers miss; the asker's
    engine serves it — correct bytes, miss counted, never an error."""
    p, payload = _fixture(tmp_path)
    A, B = StromContext(_cfg()), StromContext(_cfg())
    try:
        addr = A.serve_peers()  # A serves but never warmed anything
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        got = B.pread(p, 0, 4096)
        assert bytes(got) == payload[:4096].tobytes()
        st = B.peer_tier.stats()
        assert st["peer_misses"] >= 1 and st["peer_errors"] == 0
        assert A.peer_server.stats()["peer_serve_misses"] >= 1
    finally:
        A.close()
        B.close()


def _wait_stats(server, pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while True:
        st = server.stats()
        if pred(st) or time.monotonic() >= deadline:
            return st
        time.sleep(0.01)


def test_peer_zc_serve_bit_identical(tmp_path):
    """The zero-copy exporter (dist_send_zc, ISSUE 16) is wire-compatible:
    a zc server serves the same bytes to an unmodified client, counts them
    under peer_zc_bytes, and never touches the bounce path."""
    p, payload = _fixture(tmp_path)
    A = StromContext(_cfg(dist_send_zc=True))
    B = StromContext(_cfg())
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        b0 = B.engine.stats().get("bytes_read", 0)
        # one send below the MSG_ZEROCOPY threshold, one above it: both
        # ride the pinned-view path, the large one with the flag
        for lo, n in ((1024, 8192), (64 << 10, 128 * 1024)):
            got = B.pread(p, lo, n)
            assert bytes(got) == payload[lo:lo + n].tobytes()
        assert B.engine.stats().get("bytes_read", 0) - b0 == 0
        # the server tallies AFTER reaping zc completions, a beat after the
        # client has its bytes — poll instead of racing it
        st = _wait_stats(A.peer_server, lambda s: s["peer_serves"] >= 2)
        assert st["peer_zc_bytes"] + st["peer_sendfile_bytes"] \
            >= 8192 + 128 * 1024
        assert st["peer_copy_bytes"] == 0
        assert st["peer_serves"] == 2
    finally:
        A.close()
        B.close()


def test_peer_zc_serves_spilled_extents_via_sendfile(tmp_path):
    """A zc server whose extent demoted to the spill tier ships it with
    sendfile(2) — correct bytes, no bounce, counted separately."""
    p, payload = _fixture(tmp_path)
    # cache far smaller than the file: the head of the sequential read is
    # evicted into the spill file by the time the tail is admitted
    A = StromContext(_cfg(hot_cache_bytes=96 << 10, spill_bytes=8 << 20,
                          spill_dir=str(tmp_path), dist_send_zc=True))
    B = StromContext(_cfg())
    try:
        addr = A.serve_peers()
        for off in range(0, payload.nbytes, 32 << 10):
            A.pread(p, off, 32 << 10)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        got = B.pread(p, 0, 64 << 10)
        assert bytes(got) == payload[:64 << 10].tobytes()
        st = _wait_stats(A.peer_server, lambda s: s["peer_serves"] >= 1)
        assert st["peer_copy_bytes"] == 0
        assert st["peer_sendfile_bytes"] > 0
        assert st["peer_sendfile_bytes"] + st["peer_zc_bytes"] == 64 << 10
    finally:
        A.close()
        B.close()


def test_cacheless_context_still_probes_peers(tmp_path):
    """A peered context WITHOUT a hot cache still rides the peer tier
    (the consult handles cache=None)."""
    p, payload = _fixture(tmp_path)
    A = StromContext(_cfg())
    B = StromContext(_cfg(hot_cache_bytes=0))
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        b0 = B.engine.stats().get("bytes_read", 0)
        got = B.pread(p, 0, 4096)
        assert bytes(got) == payload[:4096].tobytes()
        assert B.engine.stats().get("bytes_read", 0) - b0 == 0
        assert B.peer_tier.stats()["peer_hit_bytes"] == 4096
    finally:
        A.close()
        B.close()


def test_killed_peer_mid_serve_degrades_bit_identical(tmp_path):
    """A peer that dies mid-frame (partial response, then hangup) costs a
    counted error and an engine fallback — the delivered bytes are
    bit-identical to a peer-less read."""
    p, payload = _fixture(tmp_path)

    # a rogue "peer": accepts, reads the request, sends HALF a frame
    # header's promised payload, then slams the connection
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(4)
    addr = f"127.0.0.1:{lsock.getsockname()[1]}"
    stop = threading.Event()

    def rogue():
        while not stop.is_set():
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            try:
                recv_frame(conn)
                conn.sendall(struct.pack("!I", 4097) + b"\x00" * 100)
            except (OSError, PeerProtocolError):
                pass
            finally:
                conn.close()  # mid-stream hangup

    t = threading.Thread(target=rogue, name="test-rogue-peer", daemon=True)
    t.start()
    B = StromContext(_cfg())
    try:
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        got = B.pread(p, 0, 4096)
        assert bytes(got) == payload[:4096].tobytes()
        assert B.peer_tier.stats()["peer_errors"] >= 1
    finally:
        stop.set()
        lsock.close()
        B.close()
        t.join(timeout=5)


def test_dead_peer_refused_connect_falls_back(tmp_path):
    p, payload = _fixture(tmp_path)
    port = _free_port()
    B = StromContext(_cfg())
    try:
        B.attach_peers({0: f"127.0.0.1:{port}"}, owner_fn=lambda path: 0)
        got = B.pread(p, 0, 4096)
        assert bytes(got) == payload[:4096].tobytes()
        assert B.peer_tier.stats()["peer_errors"] >= 1
    finally:
        B.close()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- breaker lifecycle (fake clock) ------------------------------------------

def test_peer_breaker_trip_and_recovery(tmp_path):
    p, payload = _fixture(tmp_path)
    port = _free_port()
    now = [1000.0]
    tier = PeerTier({0: f"127.0.0.1:{port}"}, owner_fn=lambda path: 0,
                    timeout_s=0.2, clock=lambda: now[0],
                    breaker_kwargs=dict(min_events=4, cooldown_s=1.0,
                                        half_open_successes=2))
    A = None
    try:
        # nothing listens: 4 straight failures trip the breaker OPEN
        for _ in range(4):
            assert tier.fetch(p, 0, 4096) is None
        st = tier.stats()
        assert st["peer_errors"] == 4
        assert st["peer_breaker_trips"] == 1
        assert st["peer_breaker_open"] == 1
        # open: fetches short-circuit (skips, no new errors)
        assert tier.fetch(p, 0, 4096) is None
        assert tier.stats()["peer_errors"] == 4
        assert tier.stats()["peer_skips"] >= 1

        # the peer comes back at the same address; cooldown elapses
        A = StromContext(_cfg())
        A.serve_peers(port=port)
        A.pread(p, 0, payload.nbytes)
        now[0] += 1.5
        # half-open probes ride real fetches; 2 successes close it
        for _ in range(2):
            got = tier.fetch(p, 0, 4096)
            assert got is not None
            assert bytes(got) == payload[:4096].tobytes()
        assert tier.stats()["peer_breaker_open"] == 0
        assert next(iter(tier.peers_info().values()))["state"] == "closed"
    finally:
        tier.close()
        if A is not None:
            A.close()


# -- peer-op fault matchers + chaos_net --------------------------------------

def test_peer_fault_kinds_injected(tmp_path):
    """errno/hangup/short_read peer rules each produce a counted failure
    + engine fallback; latency delays but succeeds."""
    p, payload = _fixture(tmp_path)
    for kind, extra in (("errno", dict(err="ECONNREFUSED")),
                        ("hangup", {}),
                        ("short_read", dict(short_frac=0.5))):
        plan = FaultPlan([FaultRule(kind, op="peer", times=1, **extra)])
        A, B = StromContext(_cfg()), StromContext(_cfg())
        try:
            addr = A.serve_peers()
            A.pread(p, 0, payload.nbytes)
            B.attach_peers({0: addr}, owner_fn=lambda path: 0)
            B.peer_tier._plan = plan
            got = B.pread(p, 0, 4096)  # injected failure -> engine
            assert bytes(got) == payload[:4096].tobytes(), kind
            assert B.peer_tier.stats()["peer_errors"] == 1, kind
            # rule exhausted (times=1): the next fetch serves peer-side
            got2 = B.pread(p, 8192, 4096)
            assert bytes(got2) == payload[8192:8192 + 4096].tobytes()
            assert B.peer_tier.stats()["peer_hits"] == 1, kind
        finally:
            A.close()
            B.close()


def test_peer_latency_fault_still_serves(tmp_path):
    p, payload = _fixture(tmp_path)
    plan = FaultPlan([FaultRule("latency", op="peer", times=1,
                                latency_s=0.05)])
    A, B = StromContext(_cfg()), StromContext(_cfg())
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        B.peer_tier._plan = plan
        t0 = time.perf_counter()
        got = B.pread(p, 0, 4096)
        assert time.perf_counter() - t0 >= 0.05
        assert bytes(got) == payload[:4096].tobytes()
        assert B.peer_tier.stats()["peer_hits"] == 1
        assert B.peer_tier.stats()["peer_errors"] == 0
    finally:
        A.close()
        B.close()


def test_chaos_net_preset_shape_and_spec():
    plan = FaultPlan.from_spec("chaos_net:7")
    assert plan.seed == 7
    assert all(r.op == "peer" for r in plan.rules)
    kinds = {r.kind for r in plan.rules}
    assert kinds == {"errno", "hangup", "latency", "short_read"}
    # determinism: same seed + same op stream = same injections
    a, b = FaultPlan.chaos_net(3), FaultPlan.chaos_net(3)
    seq_a = [a.decide(path="x", offset=0, length=64, op="peer")
             for _ in range(50)]
    seq_b = [b.decide(path="x", offset=0, length=64, op="peer")
             for _ in range(50)]
    assert [f and f.kind for f in seq_a] == [f and f.kind for f in seq_b]


def test_peer_rules_consume_no_engine_draws():
    """Interleaved engine reads must not perturb the peer fault stream
    (op-mismatched rules consume no RNG draw — the ISSUE 13 contract
    extended to the peer op)."""
    a, b = FaultPlan.chaos_net(5), FaultPlan.chaos_net(5)
    seq_a = []
    for i in range(60):
        if i % 2:
            # mismatched op: must not draw
            assert a.decide(path="x", offset=0, length=64, op="read") is None
        else:
            f = a.decide(path="x", offset=0, length=64, op="peer")
            seq_a.append(f and f.kind)
    seq_b = [b.decide(path="x", offset=0, length=64, op="peer")
             for _ in range(30)]
    assert seq_a == [f and f.kind for f in seq_b]


def test_chaos_net_pipeline_bit_identical(tmp_path):
    """A context reading THROUGH chaos_net-injected peer faults delivers
    bit-identical data (every injected network failure degrades to the
    local engine)."""
    p, payload = _fixture(tmp_path)
    A = StromContext(_cfg())
    B = StromContext(_cfg(fault_plan="chaos_net:1"))
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        out = bytearray()
        for off in range(0, 64 * 1024, 4096):
            out += bytes(B.pread(p, off, 4096))
        assert bytes(out) == payload[: 64 * 1024].tobytes()
        st = B.peer_tier.stats()
        assert st["peer_hits"] + st["peer_errors"] + st["peer_skips"] > 0
    finally:
        A.close()
        B.close()


def test_hangup_rule_on_engine_op_degrades_to_errno(tmp_path):
    """A direction-less hangup rule hitting an ENGINE op completes as a
    transient errno (retried), never a swallowed completion."""
    p, payload = _fixture(tmp_path)
    plan_doc = ('{"rules": [{"kind": "hangup", "times": 1, '
                '"err": "EIO"}]}')
    ctx = StromContext(_cfg(fault_plan=plan_doc, io_retries=2))
    try:
        got = ctx.pread(p, 0, 8192)
        assert bytes(got) == payload[:8192].tobytes()
    finally:
        ctx.close()


# -- stats exposure ----------------------------------------------------------

def test_dist_stats_section_single_sourced(tmp_path):
    p, payload = _fixture(tmp_path)
    A, B = StromContext(_cfg()), StromContext(_cfg())
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        B.pread(p, 0, 4096)
        merged = {**B.stats(sections=["dist"])["dist"],
                  **A.stats(sections=["dist"])["dist"]}
        assert set(merged) == set(DIST_FIELDS)
        # a context with neither tier nor server has no section
        C = StromContext(_cfg())
        try:
            assert "dist" not in C.stats()
        finally:
            C.close()
    finally:
        A.close()
        B.close()


def test_serve_peers_idempotent_and_closed_refused(tmp_path):
    ctx = StromContext(_cfg())
    addr = ctx.serve_peers()
    assert ctx.serve_peers() == addr
    ctx.close()
    with pytest.raises(RuntimeError):
        ctx.serve_peers()


# -- the launcher: N-process bit-identity + zero-duplicate-read --------------

@pytest.mark.parametrize("nproc", [2, 4])
def test_multiprocess_ingest_bit_identical(tmp_path, nproc):
    """Subprocess N-process runs: every worker's batch stream must be
    bit-identical to the single-process pipeline's corresponding rows,
    with ZERO duplicate SSD reads during ingest (owned rows = local RAM,
    peer rows = the extent service) and real peer traffic flowing."""
    data = str(tmp_path / "data")
    make_fixture(data, files=4, records=48, seq_len=16)
    paths = sorted(os.path.join(data, f) for f in os.listdir(data)
                   if f.endswith(".bin"))
    ref = reference_shard_hashes(paths, 16, nproc, 8, 4, seed=0)
    results = launch_local(nproc, data, str(tmp_path / "run"),
                           steps=4, batch=8, seq_len=16, seed=0)
    assert len(results) == nproc
    for r, res in enumerate(results):
        assert res.get("rc") == 0 and res.get("ok"), \
            f"worker {r}: {res.get('tail', res)}"
        assert res["sha256"] == ref[r], f"worker {r} diverged"
        assert res["engine_ingest_bytes"] == 0, \
            f"worker {r} re-read the SSD during ingest: {res}"
        assert res["peer_errors"] == 0, res
    assert sum(r["peer_hit_bytes"] for r in results) > 0
    assert sum(r["peer_hit_bytes"] for r in results) == \
        sum(r["peer_served_bytes"] for r in results)


def test_measure_ingest_fields(tmp_path):
    res = measure_ingest(2, str(tmp_path), steps=3, batch=8, seq_len=16)
    assert res["dist_ok"] == 1
    assert res["dist_peer_hit_ratio"] > 0
    assert res["dist_engine_ingest_bytes"] == 0
    # every DIST_BENCH_FIELDS column the arm copies is either produced
    # here or derived by the arm itself (single-pass comparison keys +
    # the fabric v2 batched-vs-unbatched A/B, ISSUE 20)
    arm_derived = {"dist_single_items_per_s", "dist_vs_single",
                   "dist_batch_vs_single", "dist_unbatched_items_per_s"}
    for k in DIST_BENCH_FIELDS:
        assert k in res or k in arm_derived, k


def test_owner_map_deterministic_and_balanced(tmp_path):
    data = str(tmp_path / "data")
    paths = make_fixture(data, files=6, records=30, seq_len=16)
    o1, o2 = owner_of(paths, 3), owner_of(paths, 3)
    assert o1 == o2
    assert set(o1.values()) == {0, 1, 2}
