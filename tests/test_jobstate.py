"""StepToken round-trips (ISSUE 14 satellite): capture/restore bit-identity
mid-epoch and across the epoch boundary, warm-cache resume serving with
zero source-engine reads, warm-hint replay into a fresh context, and
resume-after-failed-save falling back to the prior commit."""

import json
import os

import numpy as np
import pytest

from strom.ckpt.jobstate import (RESUME_FIELDS, StepToken,
                                 capture_warm_state, restore_warm_state,
                                 set_resume_gauges)
from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.pipelines.base import Pipeline, resolve_state
from strom.pipelines.sampler import EpochShuffleSampler, SamplerState

KiB = 1024
MiB = 1024 * KiB


def _pipe(records=64, batch=4, seed=5, make=None, depth=2, **kw):
    s = EpochShuffleSampler(records, batch, seed=seed)
    return Pipeline(s, make or (lambda idx, serial: (serial, idx.copy())),
                    depth=depth, **kw)


class TestTokenRoundTrip:
    def test_json_and_file_round_trip(self, tmp_path):
        t = StepToken(sampler=SamplerState(epoch=3, batch_in_epoch=7,
                                           seed=11),
                      consumed=55, prefetch_depth=4,
                      fingerprint={"paths": ["a"], "sizes": [1]},
                      warm={"cache": [["a", 0, 64]]}, extra={"k": 1})
        t2 = StepToken.from_dict(json.loads(json.dumps(t.to_dict())))
        assert t2 == t
        p = str(tmp_path / "tok.json")
        t.save(p)
        assert StepToken.load(p) == t

    def test_unknown_version_refused(self):
        with pytest.raises(ValueError, match="version"):
            StepToken.from_dict({"version": 99, "sampler": {}})

    @pytest.mark.parametrize("consume", [5, 16, 21])
    def test_restore_continues_bit_identical(self, consume):
        """Mid-epoch (5), exactly at the epoch boundary (16 = bpe), and
        mid-epoch-2 (21): the restored stream equals the uninterrupted
        one, serial for serial, index for index."""
        p = _pipe()          # bpe = 16
        for _ in range(consume):
            next(p)
        tok = p.token()
        assert tok.consumed == consume
        ref = [next(p) for _ in range(20)]
        p.restore(tok)
        got = [next(p) for _ in range(20)]
        for (sa, ia), (sb, ib) in zip(ref, got):
            assert sa == sb
            np.testing.assert_array_equal(ia, ib)
        p.close()

    def test_restore_into_fresh_pipeline(self):
        """The restart shape: a NEW pipeline object (fresh process),
        restored from the old one's token, continues its stream."""
        p1 = _pipe()
        for _ in range(9):
            next(p1)
        tok = p1.token()
        ref = [next(p1) for _ in range(10)]
        p1.close()
        p2 = _pipe().restore(tok)
        got = [next(p2) for _ in range(10)]
        for (sa, ia), (sb, ib) in zip(ref, got):
            assert sa == sb
            np.testing.assert_array_equal(ia, ib)
        p2.close()

    def test_restore_refuses_wrong_seed_and_dataset(self):
        p = _pipe(seed=5)
        tok = p.token()
        p.close()
        other = _pipe(seed=6)
        with pytest.raises(ValueError, match="seed"):
            other.restore(tok)
        other.close()
        tok2 = StepToken(sampler=tok.sampler, consumed=tok.consumed,
                         fingerprint={"paths": ["x"], "sizes": [1]})
        fp = {"paths": ["y"], "sizes": [2]}
        wrong = _pipe(seed=5, fingerprint=fp)
        with pytest.raises(ValueError, match="different dataset"):
            wrong.restore(tok2)
        wrong.close()

    def test_resolve_state_accepts_token(self, tmp_path):
        p = str(tmp_path / "d.bin")
        np.zeros(1024, np.uint8).tofile(p)
        fp_tok = StepToken(
            sampler=SamplerState(epoch=1, batch_in_epoch=2, seed=3),
            consumed=10,
            fingerprint={"paths": [p], "sizes": [1024]})
        state, fp = resolve_state((p,), seed=3, resume_from=fp_tok)
        assert state.epoch == 1 and state.batch_in_epoch == 2
        bad = StepToken(sampler=fp_tok.sampler, consumed=10,
                        fingerprint={"paths": [p], "sizes": [999]})
        with pytest.raises(ValueError, match="different dataset"):
            resolve_state((p,), seed=3, resume_from=bad)

    def test_token_carries_prefetch_depth(self):
        p = _pipe(depth=3)
        next(p)
        tok = p.token()
        assert tok.prefetch_depth == 3
        p.restore(tok)
        assert p.prefetch_depth == 3
        p.close()

    def test_resume_gauges_mirror(self):
        from strom.utils.stats import global_stats

        set_resume_gauges({k: i for i, k in enumerate(RESUME_FIELDS)})
        assert global_stats.gauge("resume_ok").value == 0
        assert global_stats.gauge("resume_kill_step").value == 1


class TestWarmResume:
    def _ctx(self, tmp_path, **kw):
        return StromContext(StromConfig(
            engine="python", queue_depth=8, num_buffers=16,
            slab_pool_bytes=32 * MiB, hot_cache_bytes=8 * MiB,
            hot_cache_admit="always", spill_dir=str(tmp_path), **kw))

    def test_warm_cache_resume_zero_source_reads(self, tmp_path):
        """The satellite's acceptance shape: a pipeline restored from a
        StepToken over an already-warm cache serves the continued stream
        with ZERO additional source-engine reads."""
        ctx = self._ctx(tmp_path)
        try:
            p = str(tmp_path / "src.bin")
            data = np.random.default_rng(0).integers(
                0, 256, 1 * MiB, dtype=np.uint8)
            data.tofile(p)
            step = 64 * KiB
            n_rec = len(data) // step

            def make(idx, serial):
                out = [np.asarray(ctx.pread(p, offset=int(i) * step,
                                            length=step)) for i in idx]
                return serial, np.stack(out)

            pipe = Pipeline(EpochShuffleSampler(n_rec, 2, seed=1), make,
                            depth=1)
            bpe = n_rec // 2
            for _ in range(bpe):          # epoch 1: admit everything
                next(pipe)
            tok = pipe.token(ctx, warm_state=True)
            assert tok.warm and tok.warm["cache"]
            eng0 = ctx.engine.stats().get("bytes_read", 0)
            pipe.restore(tok)
            got = [next(pipe) for _ in range(bpe)]  # epoch 2, warm
            assert len(got) == bpe
            assert ctx.engine.stats().get("bytes_read", 0) == eng0, \
                "warm-cache resume reached the source engine"
            pipe.close()
        finally:
            ctx.close()

    def test_warm_hints_replay_into_fresh_context(self, tmp_path):
        """Cross-process shape: hints captured in ctx A, replayed into a
        COLD ctx B (one warming pass, background class); the demand reads
        after it add zero engine bytes."""
        p = str(tmp_path / "src.bin")
        data = np.random.default_rng(1).integers(
            0, 256, 512 * KiB, dtype=np.uint8)
        data.tofile(p)
        ctx_a = self._ctx(tmp_path)
        try:
            for off in range(0, len(data), 64 * KiB):
                ctx_a.pread(p, offset=off, length=64 * KiB)
            warm = capture_warm_state(ctx_a)
            assert warm and warm["cache"]
        finally:
            ctx_a.close()
        ctx_b = self._ctx(tmp_path)
        try:
            warmed = restore_warm_state(ctx_b, warm)
            assert warmed > 0
            eng0 = ctx_b.engine.stats().get("bytes_read", 0)
            for off in range(0, len(data), 64 * KiB):
                back = ctx_b.pread(p, offset=off, length=64 * KiB)
                np.testing.assert_array_equal(back,
                                              data[off: off + 64 * KiB])
            assert ctx_b.engine.stats().get("bytes_read", 0) == eng0
        finally:
            ctx_b.close()

    def test_warm_hints_skip_vanished_sources(self, tmp_path):
        ctx = self._ctx(tmp_path)
        try:
            gone = str(tmp_path / "gone.bin")
            assert restore_warm_state(
                ctx, {"cache": [[gone, 0, 4096]]}) == 0
        finally:
            ctx.close()


class TestFailedSaveFallback:
    def test_resume_after_failed_save_uses_prior_commit(self, tmp_path):
        """ISSUE 14 satellite: save at step 8 commits; the save at step 12
        fails (write chaos past an op window); a restart resumes from the
        step-8 token — prior commit, bit-identical stream."""
        import jax.numpy as jnp

        from strom.ckpt import (AsyncCheckpointer, CkptAsyncError,
                                last_committed, restore_checkpoint)
        from strom.ckpt.jobstate import TOKEN_KEY

        d = str(tmp_path / "ckpt")
        # each 256KB save stages 2 write ops at 128KB blocks: ops 0-1 are
        # the step-8 save (clean), everything later fails
        plan = json.dumps({"seed": 0, "rules": [
            {"kind": "errno", "op": "write", "op_lo": 2, "err": "EIO"}]})
        ctx = StromContext(StromConfig(
            engine="python", queue_depth=8, num_buffers=16,
            slab_pool_bytes=32 * MiB, fault_plan=plan, io_retries=1))
        try:
            pipe = _pipe(seed=9)
            cp = AsyncCheckpointer(ctx, d)
            state8 = None
            for _ in range(8):
                next(pipe)
            cp.save({"w": jnp.arange(1 << 16, dtype=jnp.float32)},
                    extra={TOKEN_KEY: pipe.token().to_dict()})
            cp.wait()                       # step-8 commit lands
            for _ in range(4):
                next(pipe)
            cp.save({"w": jnp.arange(1 << 16, dtype=jnp.float32)},
                    extra={TOKEN_KEY: pipe.token().to_dict()})
            with pytest.raises(CkptAsyncError):
                cp.wait()                   # step-12 commit failed
            ref = [next(pipe) for _ in range(8)]
            cp.close(wait=False)
            pipe.close()
            # the restart: prior commit's token, stream from step 8
            lc = last_committed(d)
            assert lc is not None
            tok = StepToken.from_manifest(lc[1])
            assert tok.consumed == 8
            state8 = restore_checkpoint(
                ctx, lc[0], {"w": jnp.zeros((1 << 16,), jnp.float32)},
                verify=True)
            assert state8 is not None
            fresh = _pipe(seed=9).restore(tok)
            replay = [next(fresh) for _ in range(12)]  # 8..19
            for (sa, ia), (sb, ib) in zip(ref, replay[4:]):
                assert sa == sb
                np.testing.assert_array_equal(ia, ib)
            fresh.close()
        finally:
            ctx.close()
