"""memcpy_ssd2tpu end-to-end on the fake 8-device CPU mesh: integrity vs
open().read() golden bytes, sharded assembly, async handles, RAID0 sources
(SURVEY.md §4.2 Integrity + Device delivery rows)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from strom.config import StromConfig
from strom.delivery.core import StripedFile, StromContext


@pytest.fixture()
def ctx(engine_name):
    c = StromContext(StromConfig(engine=engine_name, queue_depth=16, num_buffers=16))
    yield c
    c.close()


def test_sync_single_device(ctx, data_file):
    path, data = data_file
    arr = ctx.memcpy_ssd2tpu(path, length=len(data) // 2 * 2, dtype=np.uint8)
    assert isinstance(arr, jax.Array)
    np.testing.assert_array_equal(np.asarray(arr), data[: len(data) // 2 * 2])


def test_sync_shaped_dtype(ctx, data_file):
    path, data = data_file
    arr = ctx.memcpy_ssd2tpu(path, shape=(1024, 256), dtype=np.float32)
    golden = data[: 1024 * 256 * 4].view(np.float32).reshape(1024, 256)
    np.testing.assert_array_equal(np.asarray(arr), golden)


def test_sync_offset_read(ctx, data_file):
    path, data = data_file
    arr = ctx.memcpy_ssd2tpu(path, offset=12345, length=4096)
    np.testing.assert_array_equal(np.asarray(arr), data[12345:12345 + 4096])


def test_sharded_batch_axis(ctx, data_file):
    path, data = data_file
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    arr = ctx.memcpy_ssd2tpu(path, shape=(16, 1024), dtype=np.uint8, sharding=sharding)
    assert arr.sharding == sharding
    golden = data[: 16 * 1024].reshape(16, 1024)
    np.testing.assert_array_equal(np.asarray(arr), golden)
    # every device holds exactly its shard
    for shard in arr.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), golden[shard.index])


def test_sharded_2d(ctx, data_file):
    path, data = data_file
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "tp"))
    sharding = NamedSharding(mesh, P("dp", "tp"))
    arr = ctx.memcpy_ssd2tpu(path, shape=(8, 512), dtype=np.float32, sharding=sharding)
    golden = data[: 8 * 512 * 4].view(np.float32).reshape(8, 512)
    np.testing.assert_array_equal(np.asarray(arr), golden)


def test_replicated(ctx, data_file):
    path, data = data_file
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
    sharding = NamedSharding(mesh, P(None))
    arr = ctx.memcpy_ssd2tpu(path, shape=(256,), dtype=np.uint8, sharding=sharding)
    np.testing.assert_array_equal(np.asarray(arr), data[:256])


def test_async_handle(ctx, data_file):
    path, data = data_file
    h = ctx.memcpy_ssd2tpu(path, length=1024 * 1024, async_=True)
    arr = h.result(timeout=30)
    assert h.done()
    np.testing.assert_array_equal(np.asarray(arr), data[: 1024 * 1024])


def test_async_many_in_flight(ctx, data_file):
    path, data = data_file
    handles = [ctx.memcpy_ssd2tpu(path, offset=i * 65536, length=65536, async_=True)
               for i in range(8)]
    for i, h in enumerate(handles):
        np.testing.assert_array_equal(
            np.asarray(h.result(timeout=30)), data[i * 65536:(i + 1) * 65536])


def test_striped_source(ctx, tmp_path, rng):
    n, chunk = 4, 8192
    logical = rng.integers(0, 256, size=n * chunk * 6, dtype=np.uint8)
    members = []
    for m in range(n):
        mdata = bytearray()
        for ci in range(m, len(logical) // chunk, n):
            mdata.extend(logical[ci * chunk:(ci + 1) * chunk])
        p = tmp_path / f"m{m}.bin"
        p.write_bytes(bytes(mdata))
        members.append(str(p))
    sf = StripedFile(tuple(members), chunk)
    assert sf.size == len(logical)
    arr = ctx.memcpy_ssd2tpu(sf, length=len(logical))
    np.testing.assert_array_equal(np.asarray(arr), logical)


def test_striped_sharded(ctx, tmp_path, rng):
    n, chunk = 2, 4096
    logical = rng.integers(0, 256, size=n * chunk * 8, dtype=np.uint8)
    members = []
    for m in range(n):
        mdata = bytearray()
        for ci in range(m, len(logical) // chunk, n):
            mdata.extend(logical[ci * chunk:(ci + 1) * chunk])
        p = tmp_path / f"sm{m}.bin"
        p.write_bytes(bytes(mdata))
        members.append(str(p))
    sf = StripedFile(tuple(members), chunk)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    shape = (16, len(logical) // 16)
    arr = ctx.memcpy_ssd2tpu(sf, shape=shape, dtype=np.uint8, sharding=sharding)
    np.testing.assert_array_equal(np.asarray(arr), logical.reshape(shape))


def test_ssd2host_plain(ctx, data_file):
    """memcpy_ssd2host: the delivered path stopped at the device_put
    boundary — returns the bytes zero-copy in a host array."""
    path, data = data_file
    arr = ctx.memcpy_ssd2host(path, length=len(data) // 2 * 2)
    assert isinstance(arr, np.ndarray)
    np.testing.assert_array_equal(arr, data[: len(data) // 2 * 2])
    # shaped/dtype/offset forms match the ssd2tpu semantics
    arr = ctx.memcpy_ssd2host(path, shape=(512, 128), dtype=np.float32)
    np.testing.assert_array_equal(
        arr, data[: 512 * 128 * 4].view(np.float32).reshape(512, 128))
    arr = ctx.memcpy_ssd2host(path, offset=12345, length=4096)
    np.testing.assert_array_equal(arr, data[12345:12345 + 4096])


def test_ssd2host_out_buffer(ctx, data_file):
    """out=: the caller's preallocated (registrable) dest IS the returned
    array — zero-copy all the way, like the raw bench arm."""
    from strom.delivery.buffers import alloc_aligned, buf_addr

    path, data = data_file
    n = 1 << 20
    dest = alloc_aligned(n)
    ctx.engine.register_dest(dest)
    arr = ctx.memcpy_ssd2host(path, length=n, out=dest)
    assert buf_addr(arr) == buf_addr(dest)  # same memory, no bounce
    np.testing.assert_array_equal(arr, data[:n])
    # too-small out refuses instead of short-reading
    with pytest.raises(ValueError, match="holds"):
        ctx.memcpy_ssd2host(path, length=n, out=alloc_aligned(n // 2))
    # strided out refuses instead of silently reading into a hidden copy
    with pytest.raises(ValueError, match="contiguous"):
        ctx.memcpy_ssd2host(path, length=n, out=alloc_aligned(2 * n)[::2])


def test_bench_ssd2host_smoke(tmp_path, rng, engine_name):
    """The strom-bench ssd2host subcommand's phase function: both arms run,
    the ratio is finite, and the fields bench.py consumes are present."""
    import argparse

    from strom.cli import bench_ssd2host

    n = 4 << 20
    data = rng.integers(0, 256, n, dtype=np.uint8)
    p = tmp_path / "ratio.bin"
    data.tofile(p)
    res = bench_ssd2host(argparse.Namespace(
        file=str(p), size=n, block=128 * 1024, depth=8, iters=2,
        engine=engine_name, tmpdir=str(tmp_path), json=True))
    assert res["bench"] == "ssd2host" and res["bytes"] == n
    assert res["raw_gbps"] > 0 and res["host_gbps"] > 0
    assert res["vs_raw"] > 0 and res["passes"] == 2
    # per-pass audit arrays: one entry per pass, best == max (VERDICT.md
    # r4 next #3)
    assert len(res["raw_gbps_passes"]) == 2
    assert len(res["host_gbps_passes"]) == 2
    assert res["raw_gbps"] == max(res["raw_gbps_passes"])
    assert res["host_gbps"] == max(res["host_gbps_passes"])


def test_bench_ssd2host_raid_smoke(tmp_path, rng, engine_name):
    """--raid: the framework arm reads the whole logical file through the
    striped alias byte-exactly (checked via memcpy_ssd2host against the
    source), and the phase reports the striped-shape fields."""
    import argparse

    from strom.cli import bench_ssd2host

    n = 4 << 20
    data = rng.integers(0, 256, n, dtype=np.uint8)
    p = tmp_path / "ratio_raid.bin"
    data.tofile(p)
    chunk = 64 * 1024
    res = bench_ssd2host(argparse.Namespace(
        file=str(p), size=n, block=128 * 1024, depth=8, iters=2,
        engine=engine_name, tmpdir=str(tmp_path), json=True,
        raid=4, raid_chunk=chunk))
    assert res["raid_members"] == 4
    assert res["bytes"] == n  # 4MiB is a multiple of the 256KiB stripe
    assert res["raw_gbps"] > 0 and res["host_gbps"] > 0 and res["vs_raw"] > 0
    # integrity: the striped-alias host path must return the source bytes
    # (the bench arms only time; this is the correctness side)
    from strom.config import StromConfig
    from strom.delivery.core import StromContext

    ctx = StromContext(StromConfig(engine=engine_name, queue_depth=8,
                                   num_buffers=8))
    try:
        members = [f"{p}.r{i}of4.c{chunk}" for i in range(4)]
        virt = str(tmp_path / "ratio.raid0")
        ctx.register_striped(virt, members, chunk, size=n)
        got = ctx.memcpy_ssd2host(virt, length=n)
        np.testing.assert_array_equal(got, data)
    finally:
        ctx.close()


def test_ssd2host_striped_alias(ctx, tmp_path, rng):
    """The host path rides striped-alias resolution like the device path."""
    n, chunk = 2, 4096
    logical = rng.integers(0, 256, size=n * chunk * 4, dtype=np.uint8)
    members = []
    for m in range(n):
        mdata = bytearray()
        for ci in range(m, len(logical) // chunk, n):
            mdata.extend(logical[ci * chunk:(ci + 1) * chunk])
        p = tmp_path / f"hm{m}.bin"
        p.write_bytes(bytes(mdata))
        members.append(str(p))
    virt = str(tmp_path / "host.raid0")
    ctx.register_striped(virt, members, chunk)
    arr = ctx.memcpy_ssd2host(virt)
    np.testing.assert_array_equal(arr, logical)


def test_short_file_raises(ctx, data_file):
    path, data = data_file
    with pytest.raises(Exception):
        ctx.memcpy_ssd2tpu(path, length=len(data) + 4096)


def test_context_survives_failed_transfer(ctx, data_file):
    """A mid-transfer error must drain in-flight ops, not poison the engine
    for the next transfer (regression: stale completions aliasing new tags)."""
    path, data = data_file
    for _ in range(3):
        with pytest.raises(Exception):
            ctx.memcpy_ssd2tpu(path, length=len(data) + 256 * 1024)
        arr = ctx.memcpy_ssd2tpu(path, length=4096)
        np.testing.assert_array_equal(np.asarray(arr), data[:4096])


def test_module_level_api(data_file, engine_name):
    import strom

    path, data = data_file
    strom.init(StromConfig(engine=engine_name))
    try:
        arr = strom.memcpy_ssd2tpu(path, length=4096)
        np.testing.assert_array_equal(np.asarray(arr), data[:4096])
        h = strom.memcpy_ssd2tpu(path, length=4096, async_=True)
        np.testing.assert_array_equal(np.asarray(strom.memcpy_wait(h)), data[:4096])
        assert strom.buffer_info()["num_buffers"] > 0
        assert strom.stats()["engine"]["bytes_read"] >= 8192
        assert "strom_" in strom.prometheus()
        rep = strom.check_file(path)
        assert rep.size == len(data)
    finally:
        strom.close()


def test_registered_striped_alias(ctx, tmp_path, rng):
    """register_striped: reads addressed to the aliased PATH — directly or
    via an ExtentList a format reader planned against it — stripe-decode
    across the members (the md-raid0 'files keep ordinary names' contract)."""
    from strom.delivery.extents import ExtentList
    from strom.engine.raid0 import stripe_file

    n, chunk = 3, 4096
    data = rng.integers(0, 256, size=n * chunk * 4, dtype=np.uint8)
    src = tmp_path / "logical.bin"
    data.tofile(src)
    members = [str(tmp_path / f"am{i}.bin") for i in range(n)]
    stripe_file(str(src), members, chunk)
    virt = str(tmp_path / "virtual.bin")  # never exists on disk
    ctx.register_striped(virt, members, chunk)

    arr = ctx.memcpy_ssd2tpu(virt, length=len(data))
    np.testing.assert_array_equal(np.asarray(arr), data)

    el = ExtentList([(virt, 100, 5000), (virt, 9000, 300)])
    got = ctx.pread(el)
    np.testing.assert_array_equal(
        got, np.concatenate([data[100:5100], data[9000:9300]]))


def test_sourceio_readahead_windows(ctx, tmp_path, rng):
    """SourceIO must serve tarfile/pyarrow-style access (small reads, seeks
    back and forth, reads straddling the readahead window) correctly, with
    far fewer engine reads than client reads."""
    from strom.delivery.core import SourceIO

    data = rng.integers(0, 256, 100_000, dtype=np.uint8).tobytes()
    p = tmp_path / "sio.bin"
    (p).write_bytes(data)
    f = SourceIO(ctx, str(p), readahead=4096)
    # forward walk in 512B steps: one engine read per 4KiB window
    for off in range(0, 8192, 512):
        assert f.read(512) == data[off: off + 512]
    # seek back (cache miss behind the window) and straddle windows
    f.seek(100)
    assert f.read(8000) == data[100:8100]
    # read past EOF clamps; read at EOF returns b""
    f.seek(99_000)
    assert f.read(5000) == data[99_000:]
    assert f.read(10) == b""
    # SEEK_END / SEEK_CUR
    import io as _io
    f.seek(-100, _io.SEEK_END)
    assert f.read(-1) == data[-100:]
    f.seek(0)
    f.seek(50, _io.SEEK_CUR)
    assert f.read(10) == data[50:60]
    # io.IOBase semantics: negative computed positions and unknown whence
    # raise ValueError here, not a confusing EngineError/KeyError later
    with pytest.raises(ValueError):
        f.seek(-5)
    with pytest.raises(ValueError):
        f.seek(10)
        f.seek(-11, _io.SEEK_CUR)
    with pytest.raises(ValueError):
        f.seek(0, 7)


@pytest.mark.parametrize("rings", [1, 2])
def test_prometheus_engine_histogram(data_file, engine_name, rings):
    """strom.prometheus() must expose the ENGINE's counters and a valid
    cumulative read-latency histogram, not just the global counters (the
    reference exposes exactly these via its /proc node). rings=2: the
    multi-ring aggregation must keep the exposition intact — dashboards
    keyed on these series target exactly those deployments."""
    import strom
    from strom.config import StromConfig

    if rings > 1 and engine_name != "uring":
        pytest.skip("multi-ring is uring-only")
    path, data = data_file
    strom.close()
    strom.init(StromConfig(engine=engine_name, engine_rings=rings,
                           queue_depth=8, num_buffers=8))
    try:
        strom.memcpy_ssd2tpu(path, length=1 << 20).block_until_ready()
        txt = strom.prometheus()
        assert "strom_engine_read_latency_us_bucket" in txt
        assert "strom_engine_bytes_read" in txt
        assert "strom_context_ssd2tpu_bytes" in txt
        # cumulative monotonicity + +Inf == count
        counts = [int(line.rsplit(" ", 1)[1])
                  for line in txt.splitlines()
                  if line.startswith("strom_engine_read_latency_us_bucket")]
        assert counts == sorted(counts) and counts[-1] > 0
        count_line = [l for l in txt.splitlines()
                      if l.startswith("strom_engine_read_latency_us_count")]
        assert int(count_line[0].rsplit(" ", 1)[1]) == counts[-1]
    finally:
        strom.close()


def test_sharded_group_failure_drains_cleanly(ctx, tmp_path, rng):
    """Group-parallel sharded delivery: when one device group's read fails
    (EOF short read), the transfer raises EngineError only after every
    in-flight group drained, and the context stays fully usable."""
    data = rng.integers(0, 256, 64 * 1024, dtype=np.uint8)
    p = str(tmp_path / "short.bin")
    data.tofile(p)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
    sharding = NamedSharding(mesh, P("dp", None))
    from strom.engine.base import EngineError

    # plan 128KiB over 8 groups; the file holds 64KiB, so late groups fail
    with pytest.raises(EngineError):
        ctx.memcpy_ssd2tpu(p, shape=(8, 16 * 1024), dtype=np.uint8,
                           sharding=sharding)
    # the drain contract: at raise time no group read may still be in
    # flight inside the engine
    assert ctx.engine.in_flight() == 0
    # reuse after failure: the engine and executors must be intact
    arr = ctx.memcpy_ssd2tpu(p, shape=(8, 8 * 1024), dtype=np.uint8,
                             sharding=sharding)
    np.testing.assert_array_equal(
        np.asarray(arr).ravel(), data)
