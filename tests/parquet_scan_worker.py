"""One process of the N-process Parquet scan fan-out integration test
(SURVEY.md §2.3; VERDICT.md r2 missing #4 / next #7). Scan-only: CPU
backend, one local device per process, no TPU — what's under test is the
LPT unit assignment, per-process engine reads, and BOTH cross-process
reductions (XLA-collective scan-mesh sum and the allgather fallback).

Usage: parquet_scan_worker.py <pid> <nproc> <port> <parquet_path>
"""

import os
import sys


def main() -> int:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    path = sys.argv[4]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)  # exactly ONE local device per process
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"localhost:{port}", num_processes=nproc,
                               process_id=pid)
    assert jax.process_count() == nproc
    assert len(jax.devices()) == nproc

    from strom.config import StromConfig
    from strom.delivery.core import StromContext
    from strom.pipelines.parquet_scan import parquet_count_where

    # python engine: 8 concurrent processes on one core — skip the io_uring
    # setup cost; the engine path is not what this test exercises
    ctx = StromContext(StromConfig(engine="python", slab_pool_bytes=0))
    try:
        for reduce in ("collective", "allgather"):
            hits = parquet_count_where(ctx, [path], "value",
                                       lambda v: v > 0, unit_batch=2,
                                       reduce=reduce)
            print(f"worker {pid}: scan[{reduce}] hits={hits}", flush=True)
    finally:
        ctx.close()
    print(f"worker {pid}: scan fanout ok", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
