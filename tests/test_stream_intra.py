"""Intra-batch streaming parity + lifecycle (ISSUE 5 satellite): the
completion-driven read→decode→put dataflow must deliver BIT-IDENTICAL
batches to the barrier path on every engine — including batches served
fully or partially from the hot cache (instant completions) — and
cancellation-on-close must leave no leaked slab pins and no in-flight
completions."""

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.delivery.shard import Segment

MiB = 1024 * 1024

cv2 = pytest.importorskip("cv2")


@pytest.fixture(scope="module")
def mesh2():
    import jax

    from strom.parallel.mesh import make_mesh

    return make_mesh({"dp": 2}, devices=jax.devices()[:2])


@pytest.fixture(scope="module")
def wds_tar(tmp_path_factory):
    from tests.test_formats import make_wds_shard

    rng = np.random.default_rng(5)
    td = tmp_path_factory.mktemp("stream_wds")
    samples = []
    for i in range(24):
        img = rng.integers(0, 256, (48 + (i % 5), 56, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        samples.append((f"s{i:04d}", {"jpg": buf.tobytes(),
                                      "cls": str(i % 10).encode()}))
    p = str(td / "stream.tar")
    make_wds_shard(p, samples)
    return p


def _run_epochs(path, mesh2, *, stream, engine, epochs=2, batch=8,
                hot_cache=0, admit="always"):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from strom.pipelines.vision import make_wds_vision_pipeline

    sharding = NamedSharding(mesh2, P("dp", None, None, None))
    cfg = StromConfig(engine=engine, queue_depth=8, num_buffers=16,
                      hot_cache_bytes=hot_cache, hot_cache_admit=admit)
    ctx = StromContext(cfg)
    out = []
    try:
        with make_wds_vision_pipeline(ctx, [path], batch=batch,
                                      image_size=32, sharding=sharding,
                                      seed=11, decode_workers=2,
                                      stream_intra_batch=stream) as pipe:
            spe = pipe.sampler.batches_per_epoch
            for _ in range(spe * epochs):
                imgs, lbls = next(pipe)
                out.append((np.asarray(imgs), np.asarray(lbls)))
    finally:
        ctx.close()
    return out


class TestBitIdentity:
    def test_streamed_matches_barrier(self, engine_name, wds_tar, mesh2):
        """Streamed vs --no-stream over two epochs: identical bytes, every
        batch (decode order differs; contents must not)."""
        a = _run_epochs(wds_tar, mesh2, stream=True, engine=engine_name)
        b = _run_epochs(wds_tar, mesh2, stream=False, engine=engine_name)
        assert len(a) == len(b)
        for (ia, la), (ib, lb) in zip(a, b):
            np.testing.assert_array_equal(ia, ib)
            np.testing.assert_array_equal(la, lb)

    def test_streamed_engaged(self, engine_name, wds_tar, mesh2):
        """The parity above must compare the STREAMED path, not a silent
        fallback: the stream counters prove it engaged."""
        from strom.utils.stats import global_stats

        snap0 = global_stats.snapshot()
        _run_epochs(wds_tar, mesh2, stream=True, engine=engine_name,
                    epochs=1)
        snap1 = global_stats.snapshot()
        assert snap1.get("stream_batches", 0) > snap0.get("stream_batches", 0)

    def test_hot_cache_hit_and_partial_hit_batches(self, engine_name,
                                                   wds_tar, mesh2):
        """Epoch 2 under force-admit serves from the cache (full-hit
        batches = pure instant completions); a mid-run partial admission
        exercises mixed instant+engine batches. Bytes must match the
        cache-free barrier path throughout."""
        from strom.utils.stats import global_stats

        golden = _run_epochs(wds_tar, mesh2, stream=False,
                             engine=engine_name, hot_cache=0)
        snap0 = global_stats.snapshot()
        cached = _run_epochs(wds_tar, mesh2, stream=True,
                             engine=engine_name, hot_cache=64 * MiB,
                             admit="always")
        snap1 = global_stats.snapshot()
        for (ia, la), (ib, lb) in zip(cached, golden):
            np.testing.assert_array_equal(ia, ib)
            np.testing.assert_array_equal(la, lb)
        # epoch 2 was served (at least partly) as instant completions
        assert snap1.get("stream_instant_bytes", 0) \
            > snap0.get("stream_instant_bytes", 0)

    def test_partial_hit_single_batch(self, engine_name, wds_tar, mesh2,
                                      tmp_path):
        """One streamed gather whose plan is split between cached ranges
        (instant) and engine misses lands the same bytes as pread."""
        import os

        size = os.stat(wds_tar).st_size
        cfg = StromConfig(engine=engine_name, queue_depth=8, num_buffers=16,
                          hot_cache_bytes=64 * MiB, hot_cache_admit="always")
        ctx = StromContext(cfg)
        try:
            golden = np.fromfile(wds_tar, dtype=np.uint8)
            # admit only the FIRST HALF: the gather below is a partial hit
            half = size // 2 // 4096 * 4096
            ctx.hot_cache.admit(wds_tar, 0, half, golden[:half], force=True)
            from strom.delivery.buffers import alloc_aligned

            dest = alloc_aligned(size)
            g = ctx.stream_segments(wds_tar, [Segment(0, 0, size)], dest)
            ranges = []
            while not g.done:
                ranges.extend(g.poll(min_completions=1))
            assert g.finish() == size
            g.close()
            np.testing.assert_array_equal(dest[:size], golden)
            # every byte completed exactly once
            covered = np.zeros(size, dtype=bool)
            for lo, hi in ranges:
                assert not covered[lo:hi].any(), "range completed twice"
                covered[lo:hi] = True
            assert covered.all()
            assert g.instant_bytes > 0
        finally:
            ctx.close()


class TestDegenerateSamples:
    def test_zero_byte_members_dont_hang(self, engine_name, mesh2,
                                         tmp_path_factory):
        """A sample whose image AND label members are 0 bytes has NO
        extents to wait for — the streamed path must dispatch it up front
        instead of deadlocking on a byte countdown that never fires. The
        empty blob then fails decode the same way the barrier path fails
        (cv2 raises on an empty buffer; the zero-image policy only absorbs
        ValueError — pre-existing semantics, parity asserted here): both
        paths RAISE promptly, neither hangs."""
        from tests.test_formats import make_wds_shard

        rng_l = np.random.default_rng(9)
        td = tmp_path_factory.mktemp("stream_degen")
        samples = []
        for i in range(8):
            if i == 3:
                samples.append((f"s{i:04d}", {"jpg": b"", "cls": b""}))
                continue
            img = rng_l.integers(0, 256, (40, 40, 3), dtype=np.uint8)
            ok, buf = cv2.imencode(".jpg", img)
            assert ok
            samples.append((f"s{i:04d}", {"jpg": buf.tobytes(),
                                          "cls": str(i).encode()}))
        p = str(td / "degen.tar")
        make_wds_shard(p, samples)
        with pytest.raises(Exception, match="(?i)empty|imdecode|decode"):
            _run_epochs(p, mesh2, stream=True, engine=engine_name,
                        epochs=1, batch=8)
        with pytest.raises(Exception, match="(?i)empty|imdecode|decode"):
            _run_epochs(p, mesh2, stream=False, engine=engine_name,
                        epochs=1, batch=8)


class TestExposure:
    def test_stream_section_in_stats_and_metrics(self, engine_name,
                                                 wds_tar, mesh2):
        """Acceptance: the stream counters appear in ctx.stats() and the
        Prometheus exposition, with stream_batches typed as a counter."""
        from strom.delivery.stream import STREAM_FIELDS
        from strom.utils.stats import sections_prometheus

        _run_epochs(wds_tar, mesh2, stream=True, engine=engine_name,
                    epochs=1)
        ctx = StromContext(StromConfig(engine=engine_name, queue_depth=4,
                                       num_buffers=8))
        try:
            stats = ctx.stats()
            assert "stream" in stats
            sec = stats["stream"]
            # every bench column the arms copy must exist in the section
            # (stream_intra_batch is a config flag, not a stat)
            for k in STREAM_FIELDS:
                assert k in sec, k
            assert sec["stream_batches"] > 0
            text = sections_prometheus(stats)
            assert "strom_stream_stream_batches" in text
            assert "# TYPE strom_stream_stream_batches counter" in text
            assert "strom_stream_stream_tail_extent_us_bucket" in text
        finally:
            ctx.close()


class TestCancellation:
    def test_close_leaves_no_pins_or_inflight(self, engine_name, wds_tar,
                                              mesh2):
        """Closing a streamed gather mid-flight (the pipeline-teardown
        path): no hot-cache entry stays pinned, no completion stays in
        flight, the engine is reusable."""
        import os

        size = os.stat(wds_tar).st_size
        cfg = StromConfig(engine=engine_name, queue_depth=4, num_buffers=8,
                          hot_cache_bytes=64 * MiB, hot_cache_admit="always")
        ctx = StromContext(cfg)
        try:
            golden = np.fromfile(wds_tar, dtype=np.uint8)
            half = size // 2 // 4096 * 4096
            ctx.hot_cache.admit(wds_tar, 0, half, golden[:half], force=True)
            from strom.delivery.buffers import alloc_aligned

            dest = alloc_aligned(size)
            g = ctx.stream_segments(wds_tar, [Segment(0, 0, size)], dest)
            g.poll(min_completions=1)  # consume the instants at least
            g.close()  # mid-flight abandon
            assert ctx.engine.in_flight() == 0
            with ctx.hot_cache._lock:
                assert all(e.refs == 0
                           for e in ctx.hot_cache._lru.values()), \
                    "streamed gather leaked a cache pin"
            # the engine (and its lock) must be free for the next transfer
            np.testing.assert_array_equal(
                ctx.pread(wds_tar, 0, 4096), golden[:4096])
        finally:
            ctx.close()

    def test_context_close_with_live_gather(self, engine_name, wds_tar):
        """Engine close cancels the token under a live gather: no hang, no
        in-flight completions."""
        import os

        size = os.stat(wds_tar).st_size
        ctx = StromContext(StromConfig(engine=engine_name, queue_depth=4,
                                       num_buffers=8))
        from strom.delivery.buffers import alloc_aligned

        dest = alloc_aligned(size)
        g = ctx.stream_segments(wds_tar, [Segment(0, 0, size)], dest)
        # close the gather first (releases the engine lock), then the ctx —
        # the engine-level cancellation test (close with a LIVE token) is
        # TestErrorsAndCancellation.test_close_cancels_live_token
        g.close()
        assert ctx.engine.in_flight() == 0
        ctx.close()
