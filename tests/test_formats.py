"""T2 format readers: extents, rawbin records, WebDataset tar, JPEG, Parquet
(SURVEY.md §4.2 'Integrity' row: format reads == golden bytes/decodes)."""

import io
import json
import os
import tarfile

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.delivery.extents import Extent, ExtentList
from strom.formats.rawbin import TokenShardSet
from strom.formats.wds import TarIndex, WdsShardSet, split_key


@pytest.fixture()
def ctx(engine_name):
    # both engines: tar/parquet extents have 512-aligned and arbitrary offsets,
    # exactly the inputs that exercise the unaligned buffered-fd fallback
    c = StromContext(StromConfig(engine=engine_name, queue_depth=8, num_buffers=8))
    yield c
    c.close()


# ---------------------------------------------------------------- ExtentList
class TestExtentList:
    def test_locate_spans_extents(self, tmp_path):
        el = ExtentList([("a", 0, 10), ("b", 100, 5), ("a", 50, 20)])
        assert el.size == 35
        runs = list(el.locate(8, 10))
        assert [(r.path, r.offset, r.length, r.dest_offset) for r in runs] == [
            ("a", 8, 2, 0), ("b", 100, 5, 2), ("a", 50, 3, 7)]

    def test_locate_bounds(self):
        el = ExtentList([("a", 0, 10)])
        with pytest.raises(ValueError):
            list(el.locate(5, 6))
        assert list(el.locate(10, 0)) == []

    def test_slice_and_concat(self):
        el = ExtentList([("a", 0, 10), ("b", 0, 10)])
        s = el.slice(5, 10)
        assert s.size == 10
        assert s.extents == (Extent("a", 5, 5), Extent("b", 0, 5))
        assert ExtentList.concat([el, s]).size == 30

    def test_pread_gather(self, ctx, tmp_path, rng):
        a = rng.integers(0, 256, 1000, dtype=np.uint8)
        b = rng.integers(0, 256, 1000, dtype=np.uint8)
        pa_, pb = str(tmp_path / "a"), str(tmp_path / "b")
        a.tofile(pa_)
        b.tofile(pb)
        el = ExtentList([(pa_, 100, 50), (pb, 0, 200), (pa_, 900, 100)])
        got = ctx.pread(el)
        want = np.concatenate([a[100:150], b[:200], a[900:1000]])
        np.testing.assert_array_equal(got, want)

    def test_memcpy_from_extents_sharded(self, ctx, tmp_path, rng):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from strom.parallel.mesh import make_mesh

        rows = rng.integers(0, 256, (8, 256), dtype=np.uint8)
        paths = []
        for i in range(4):  # two rows per file, reversed order within file
            p = str(tmp_path / f"part{i}.bin")
            np.concatenate([rows[2 * i + 1], rows[2 * i]]).tofile(p)
            paths.append(p)
        exts = []
        for i in range(4):
            exts.append((paths[i], 256, 256))  # row 2i
            exts.append((paths[i], 0, 256))    # row 2i+1
        el = ExtentList(exts)
        mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
        arr = ctx.memcpy_ssd2tpu(el, shape=(8, 256), dtype=np.uint8,
                                 sharding=NamedSharding(mesh, P("dp", None)))
        np.testing.assert_array_equal(np.asarray(arr), rows)


# ------------------------------------------------------------------- rawbin
class TestTokenShardSet:
    def make_shards(self, tmp_path, rng, n_shards=3, tokens_per_shard=100,
                    record_tokens=9):
        paths, all_tokens = [], []
        for i in range(n_shards):
            t = rng.integers(0, 50_000, tokens_per_shard + i, dtype=np.int32)
            p = str(tmp_path / f"shard{i}.bin")
            t.tofile(p)
            paths.append(p)
            # records that survive the tail drop
            n_rec = len(t) // record_tokens
            all_tokens.append(t[: n_rec * record_tokens].reshape(n_rec, record_tokens))
        return TokenShardSet(tuple(paths), record_tokens=record_tokens), \
            np.concatenate(all_tokens)

    def test_record_count_drops_tails(self, tmp_path, rng):
        ss, golden = self.make_shards(tmp_path, rng)
        assert ss.num_records == len(golden)

    def test_locate_and_extents_roundtrip(self, ctx, tmp_path, rng):
        ss, golden = self.make_shards(tmp_path, rng)
        idx = [0, 5, 3, ss.num_records - 1]
        el = ss.extents(idx)
        got = ctx.pread(el).view(np.int32).reshape(len(idx), ss.record_tokens)
        np.testing.assert_array_equal(got, golden[idx])

    def test_sequential_batch_coalesces(self, tmp_path, rng):
        ss, _ = self.make_shards(tmp_path, rng)
        per0 = ss.records_in_shard(0)
        el = ss.extents(range(per0))  # whole first shard, in order
        assert len(el) == 1

    def test_out_of_range(self, tmp_path, rng):
        ss, _ = self.make_shards(tmp_path, rng)
        with pytest.raises(IndexError):
            ss.locate(ss.num_records)


# ------------------------------------------------------------------ wds/tar
def make_wds_shard(path, samples):
    """samples: list of (key, {ext: bytes})"""
    with tarfile.open(path, "w") as tf:
        for key, members in samples:
            for ext, data in members.items():
                info = tarfile.TarInfo(f"{key}.{ext}")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))


class TestWds:
    def test_split_key(self):
        assert split_key("a/b.cls.txt") == ("a/b", "cls.txt")
        assert split_key("img001.jpg") == ("img001", "jpg")

    def test_index_and_samples(self, tmp_path, rng):
        p = str(tmp_path / "shard0.tar")
        data = {f"s{i:03d}": {"jpg": rng.bytes(100 + i), "cls": str(i % 10).encode()}
                for i in range(5)}
        make_wds_shard(p, list(data.items()))
        idx = TarIndex.build(p)
        samples = idx.samples()
        assert [s.key for s in samples] == sorted(data)
        for s in samples:
            assert set(s.members) == {"jpg", "cls"}

    def test_member_bytes_roundtrip(self, ctx, tmp_path, rng):
        p = str(tmp_path / "shard0.tar")
        payloads = [(f"s{i}", {"jpg": rng.bytes(1000 + 17 * i)}) for i in range(4)]
        make_wds_shard(p, payloads)
        ss = WdsShardSet([p])
        for (key, members), sample in zip(payloads, ss):
            got = ctx.pread(sample.extents(["jpg"]))
            assert got.tobytes() == members["jpg"]

    def test_index_cache_roundtrip(self, tmp_path, rng):
        p = str(tmp_path / "shard0.tar")
        make_wds_shard(p, [("a", {"txt": b"hello"})])
        idx1 = TarIndex.build(p)
        assert os.path.exists(p + ".stromidx.json")
        idx2 = TarIndex.build(p)  # served from cache
        assert [m.__dict__ for m in idx1.members] == [m.__dict__ for m in idx2.members]

    def test_stale_cache_rejected(self, tmp_path):
        p = str(tmp_path / "shard0.tar")
        make_wds_shard(p, [("a", {"txt": b"hello"})])
        TarIndex.build(p)
        with open(p + ".stromidx.json") as f:
            blob = json.load(f)
        blob["tar_size"] = 1  # corrupt the validation stamp
        with open(p + ".stromidx.json", "w") as f:
            json.dump(blob, f)
        idx = TarIndex.build(p)  # falls back to a rescan
        assert idx.members[0].name == "a.txt"

    def test_zero_size_member_ok(self, ctx, tmp_path):
        """Empty members (empty captions/labels exist in real datasets) must
        yield empty reads, not crash the batch."""
        p = str(tmp_path / "shard0.tar")
        make_wds_shard(p, [("a", {"txt": b"", "bin": b"xy"})])
        ss = WdsShardSet([p])
        assert ctx.pread(ss.samples[0].extents(["txt"])).size == 0
        assert ctx.pread(ss.samples[0].extents(["txt", "bin"])).tobytes() == b"xy"

    def test_batch_extents_concat(self, ctx, tmp_path, rng):
        p = str(tmp_path / "shard0.tar")
        payloads = [(f"s{i}", {"bin": bytes([i]) * 64}) for i in range(3)]
        make_wds_shard(p, payloads)
        ss = WdsShardSet([p])
        got = ctx.pread(ss.batch_extents([2, 0], ["bin"]))
        assert got.tobytes() == bytes([2]) * 64 + bytes([0]) * 64


# -------------------------------------------------------------------- jpeg
class TestJpeg:
    def make_jpeg(self, rng, h=48, w=64):
        import cv2

        img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, 95])
        assert ok
        return img, buf.tobytes()

    def test_decode_shape_and_closeness(self, rng):
        from strom.formats.jpeg import decode_jpeg

        img, data = self.make_jpeg(rng)
        out = decode_jpeg(data)
        assert out.shape == img.shape and out.dtype == np.uint8

    def test_transforms_shapes(self, rng):
        from strom.formats.jpeg import center_crop_resize, random_resized_crop

        img = rng.integers(0, 256, (100, 80, 3), dtype=np.uint8)
        assert center_crop_resize(img, 32).shape == (32, 32, 3)
        out = random_resized_crop(img, 32, np.random.default_rng(0))
        assert out.shape == (32, 32, 3) and out.flags.c_contiguous

    def test_decode_pool(self, rng):
        from strom.formats.jpeg import DecodePool, decode_jpeg

        blobs = [self.make_jpeg(rng, 32, 32)[1] for _ in range(8)]
        with DecodePool(4) as pool:
            outs = pool.map(decode_jpeg, blobs)
        assert all(o.shape == (32, 32, 3) for o in outs)

    def test_garbage_raises(self):
        from strom.formats.jpeg import decode_jpeg

        with pytest.raises(ValueError):
            decode_jpeg(b"definitely not a jpeg")


# ----------------------------------------------------------------- parquet
class TestParquet:
    @pytest.fixture()
    def pq_file(self, tmp_path, rng):
        import pyarrow as pa
        import pyarrow.parquet as pq

        n = 10_000
        table = pa.table({
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "value": pa.array(rng.normal(size=n)),
            "flag": pa.array(rng.integers(0, 2, n).astype(bool)),
        })
        p = str(tmp_path / "data.parquet")
        pq.write_table(table, p, row_group_size=2500, compression="zstd")
        return p, table

    def test_metadata(self, pq_file):
        from strom.formats.parquet import ParquetShard

        p, table = pq_file
        shard = ParquetShard(p)
        assert shard.num_rows == table.num_rows
        assert shard.num_row_groups == 4
        assert shard.column_names == ["id", "value", "flag"]

    def test_read_row_group_projected(self, ctx, pq_file):
        from strom.formats.parquet import ParquetShard

        p, table = pq_file
        shard = ParquetShard(p)
        got = shard.read_row_group(ctx, 1, columns=["id", "value"])
        want = table.slice(2500, 2500).select(["id", "value"])
        assert got.equals(want)

    def test_no_cache_misses_on_selected_columns(self, ctx, pq_file):
        """All bytes pyarrow touches must have come through the engine."""
        from strom.utils.stats import global_stats

        from strom.formats.parquet import ParquetShard

        p, _ = pq_file
        before = global_stats.counter("parquet_cache_miss_bytes").value
        ParquetShard(p).read_row_group(ctx, 0, columns=["value"])
        assert global_stats.counter("parquet_cache_miss_bytes").value == before

    def test_empty_column_selection(self, ctx, pq_file):
        """columns=[] means zero columns (rows only), never 'all columns'."""
        from strom.formats.parquet import ParquetShard

        got = ParquetShard(pq_file[0]).read_row_group(ctx, 0, columns=[])
        assert got.num_columns == 0 and got.num_rows == 2500

    def test_footer_read_once(self, ctx, pq_file):
        from strom.formats.parquet import ParquetShard

        shard = ParquetShard(pq_file[0])
        shard.read_row_group(ctx, 0, columns=["id"])
        footer = shard._footer_bytes
        assert footer is not None
        shard.read_row_group(ctx, 1, columns=["id"])
        assert shard._footer_bytes is footer

    def test_unknown_column(self, ctx, pq_file):
        from strom.formats.parquet import ParquetShard

        with pytest.raises(KeyError):
            ParquetShard(pq_file[0]).column_chunk_extents(0, ["nope"])

    def test_all_row_groups_concat(self, ctx, pq_file):
        import pyarrow as pa

        from strom.formats.parquet import ParquetShard

        p, table = pq_file
        shard = ParquetShard(p)
        parts = [shard.read_row_group(ctx, g) for g in range(shard.num_row_groups)]
        assert pa.concat_tables(parts).equals(table)


class TestPlainDecode:
    """Direct PLAIN-page decode (formats/parquet.decode_plain_pages): the
    I/O-bound scan path — frombuffer views instead of the pyarrow round
    trip, falling back whenever the bytes can't be proven reinterpretable
    (VERDICT.md r4 next #1). Every case cross-checks against pyarrow."""

    COLS = ("a64", "a32", "i64", "i32")

    def _write(self, tmp_path, rng, name="plain.parquet", **kw):
        import pyarrow as pa
        import pyarrow.parquet as pq

        n = 50_000
        table = pa.table({
            "a64": pa.array(rng.normal(size=n)),
            "a32": pa.array(rng.normal(size=n).astype(np.float32)),
            "i64": pa.array(rng.integers(0, 1 << 40, n, dtype=np.int64)),
            "i32": pa.array(rng.integers(0, 1 << 20, n, dtype=np.int32)),
        })
        p = str(tmp_path / name)
        kw.setdefault("row_group_size", 30_000)  # 2 pages/chunk (20k-row cap)
        kw.setdefault("compression", "NONE")
        kw.setdefault("use_dictionary", False)
        pq.write_table(table, p, **kw)
        return p, table

    def _counters(self):
        from strom.utils.stats import global_stats

        snap = global_stats.snapshot()
        return (snap.get("parquet_plain_bytes", 0),
                snap.get("parquet_decode_bytes", 0))

    def _check(self, ctx, p, table, expect_plain: bool):
        from strom.formats.parquet import ParquetShard

        shard = ParquetShard(p, ctx=ctx)
        plain0, fall0 = self._counters()
        off = 0
        for g in range(shard.num_row_groups):
            got = shard.read_row_group_arrays(ctx, g, list(self.COLS))
            n = len(got[self.COLS[0]])
            for c in self.COLS:
                want = table.slice(off, n)[c].to_numpy()
                np.testing.assert_array_equal(got[c], want)
            off += n
        assert off == table.num_rows
        plain1, fall1 = self._counters()
        if expect_plain:
            assert plain1 > plain0 and fall1 == fall0
        else:
            assert plain1 == plain0 and fall1 > fall0

    def test_plain_multi_dtype_multi_page(self, ctx, tmp_path, rng):
        p, table = self._write(tmp_path, rng)
        self._check(ctx, p, table, expect_plain=True)

    def test_no_statistics_def_levels_parsed(self, ctx, tmp_path, rng):
        """Without chunk statistics the decoder must PARSE the RLE/bit-packed
        definition levels to prove no nulls, not assume."""
        p, table = self._write(tmp_path, rng, write_statistics=False)
        self._check(ctx, p, table, expect_plain=True)

    def test_snappy_falls_back(self, ctx, tmp_path, rng):
        p, table = self._write(tmp_path, rng, compression="snappy")
        self._check(ctx, p, table, expect_plain=False)

    def test_dictionary_falls_back(self, ctx, tmp_path, rng):
        p, table = self._write(tmp_path, rng, use_dictionary=True)
        self._check(ctx, p, table, expect_plain=False)

    def test_nulls_fall_back(self, ctx, tmp_path, rng):
        """A nullable column with REAL nulls: the def levels are not all
        ones, so reinterpreting the value bytes would mis-align rows — the
        decoder must detect this from the page itself and fall back."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from strom.formats.parquet import ParquetShard

        vals = [1.0, None, 3.0] * 1000
        p = str(tmp_path / "nulls.parquet")
        pq.write_table(pa.table({"n": pa.array(vals)}), p,
                       compression="NONE", use_dictionary=False,
                       write_statistics=False)
        shard = ParquetShard(p, ctx=ctx)
        plain0, fall0 = self._counters()
        got = shard.read_row_group_arrays(ctx, 0, ["n"])["n"]
        want = shard.read_row_group(ctx, 0, columns=["n"])["n"] \
            .to_numpy(zero_copy_only=False)
        np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
        np.testing.assert_array_equal(got[~np.isnan(got)],
                                      want[~np.isnan(want)])
        plain1, fall1 = self._counters()
        assert plain1 == plain0 and fall1 > fall0

    def test_malformed_chunk_bytes_fall_back_not_crash(self, ctx, tmp_path,
                                                       rng):
        """Truncated/garbage chunk bytes must surface as the controlled
        fallback signal (_PlainDecodeUnsupported), never a bare
        IndexError/ValueError out of the page walk."""
        import pyarrow.parquet as pq  # noqa: F401 (fixture dependency)

        from strom.formats.parquet import (ParquetShard,
                                           _PlainDecodeUnsupported,
                                           decode_plain_pages)

        p, _ = self._write(tmp_path, rng)
        shard = ParquetShard(p, ctx=ctx)
        rg = shard.metadata.row_group(0)
        ci = shard._col_indices(["a64"])[0]
        ext = shard.column_chunk_extents(0, ["a64"])
        good = ctx.pread(ext)
        schema_col = shard.metadata.schema.column(ci)
        for bad in (good[:7],                      # truncated mid-header
                    good[:len(good) // 2],         # truncated mid-values
                    np.frombuffer(rng.bytes(256), np.uint8),   # garbage
                    # 0x1C = (field delta 1, type struct): each byte opens
                    # a nested thrift struct — recursion-limit bomb
                    np.full(5000, 0x1C, dtype=np.uint8),
                    # crafted header with NEGATIVE comp_size (-11) and
                    # num_values (-2): without explicit guards this loops
                    # forever (cursor walks backward onto the same header,
                    # decoded never reaches total)
                    np.frombuffer(bytes([0x15, 0x00, 0x25, 0x15, 0x2C,
                                         0x15, 0x03, 0x15, 0x00, 0x00,
                                         0x00]) + b"\0" * 64, np.uint8),
                    # crafted header with a VALID comp_size (64) but
                    # num_values = -2: reaches the num_values guard
                    # specifically (the buffer above trips on comp_size
                    # first); without it, decoded += -2 never reaches
                    # total and frombuffer(count=-2) reads "all"
                    np.frombuffer(bytes([0x15, 0x00,              # type 0
                                         0x15, 0x80, 0x01,       # uncomp 64
                                         0x15, 0x80, 0x01,       # comp 64
                                         0x2C,                   # dph struct
                                         0x15, 0x03,             # n_vals -2
                                         0x15, 0x00,             # enc PLAIN
                                         0x15, 0x06,             # def RLE
                                         0x00, 0x00])            # stops
                                  + b"\0" * 80, np.uint8)):
            with pytest.raises(_PlainDecodeUnsupported):
                decode_plain_pages(rg.column(ci), schema_col, bad)

    def test_defs_all_present_run_shapes(self):
        """_defs_all_present against hand-built bit-width-1 blocks: RLE
        runs, bit-packed groups (incl. the partial last byte), and every
        way a zero bit can hide."""
        from strom.formats.parquet import _defs_all_present

        def uvarint(n: int) -> bytes:
            out = bytearray()
            while True:
                b = n & 0x7F
                n >>= 7
                out.append(b | (0x80 if n else 0))
                if not n:
                    return bytes(out)

        # RLE run of 100 ones: header = count<<1, value byte 1
        assert _defs_all_present(uvarint(100 << 1) + b"\x01", 100)
        # RLE run of zeros -> nulls
        assert not _defs_all_present(uvarint(100 << 1) + b"\x00", 100)
        # bit-packed: 2 groups of 8, all ones (header = n_groups<<1 | 1)
        assert _defs_all_present(uvarint(2 << 1 | 1) + b"\xff\xff", 16)
        # bit-packed with one zero bit in a FULL byte
        assert not _defs_all_present(uvarint(2 << 1 | 1) + b"\xff\xfe", 16)
        # bit-packed partial tail: 12 values over 2 groups; the high 4 bits
        # of byte 2 are PADDING and must be ignored...
        assert _defs_all_present(uvarint(2 << 1 | 1) + b"\xff\x0f", 12)
        # ...but a zero inside the VALID low bits must be caught
        assert not _defs_all_present(uvarint(2 << 1 | 1) + b"\xff\x07", 12)
        # mixed: RLE 8 ones then bit-packed group of 8 ones
        assert _defs_all_present(
            uvarint(8 << 1) + b"\x01" + uvarint(1 << 1 | 1) + b"\xff", 16)
        # truncated block (runs cover fewer values than num_values)
        assert not _defs_all_present(uvarint(8 << 1) + b"\x01", 16)

    def test_thrift_skip_field_types(self):
        """_thrift_struct must skip over every compact field type that can
        appear in a PageHeader (bools, doubles, binaries, lists, nested
        structs, long-form field ids) and still land on later fields."""
        from strom.formats.parquet import _thrift_struct

        buf = bytes([
            0x11,              # field 1: BOOLEAN_TRUE (value in type)
            0x17,              # field 2: double
            *([0x40] * 8),     # 8 payload bytes
            0x18, 0x03,        # field 3: binary, len 3
            0x61, 0x62, 0x63,
            0x19, 0x25,        # field 4: list of 2 i32 elements
            0x02, 0x04,        # zigzag 1, 2
            0x1C,              # field 5: nested struct
            0x15, 0x06,        # nested field 1: i32 zigzag(6)=3
            0x00,              # nested stop
            0x05, 0x0E,        # long-form id: delta 0, type i32, id=7
            0x2A,              # zigzag -> 21
            0x00,              # stop
        ])
        out, pos = _thrift_struct(memoryview(buf), 0)
        assert out[1] is True
        assert out[5] == {1: 3}
        assert out[7] == 21
        assert pos == len(buf)

    def test_single_page_is_view(self, ctx, tmp_path, rng):
        """A single-page chunk decodes to a VIEW over the engine slab (no
        copy) — the property the fast path exists for."""
        import pyarrow.parquet as pq

        from strom.formats.parquet import (ParquetShard, decode_plain_pages)

        p, table = self._write(tmp_path, rng, row_group_size=10_000)
        shard = ParquetShard(p, ctx=ctx)
        rg = shard.metadata.row_group(0)
        ext = shard.column_chunk_extents(0, ["a64"])
        buf = ctx.pread(ext)
        ci = shard._col_indices(["a64"])[0]
        pages = decode_plain_pages(rg.column(ci),
                                   shard.metadata.schema.column(ci), buf)
        assert len(pages) == 1
        assert pages[0].base is not None  # a view, not an owning copy
        np.testing.assert_array_equal(
            pages[0], table.slice(0, 10_000)["a64"].to_numpy())

    def test_logical_types_fall_back_and_agree(self, ctx, tmp_path, rng):
        """uint32 (physical INT32 + unsigned annotation), date32 and
        timestamp columns must NOT ride the raw-reinterpret fast path: a
        uint32 value past 2^31 would silently come back negative and
        date/timestamp would come back as raw ints (ADVICE.md r5 high).
        Cross-check: the routed result equals the pyarrow fallback exactly,
        values AND dtype."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from strom.formats.parquet import ParquetShard

        n = 5000
        u32 = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
        u32[0] = 2147483653  # > 2^31: the silent-reinterpretation witness
        table = pa.table({
            "u32": pa.array(u32),
            "d32": pa.array(rng.integers(0, 30000, n).astype(np.int32),
                            type=pa.date32()),
            "ts": pa.array(rng.integers(0, 1 << 48, n, dtype=np.int64),
                           type=pa.timestamp("us")),
        })
        p = str(tmp_path / "logical.parquet")
        pq.write_table(table, p, compression="NONE", use_dictionary=False)
        shard = ParquetShard(p, ctx=ctx)
        plain0, fall0 = self._counters()
        got = shard.read_row_group_arrays(ctx, 0, ["u32", "d32", "ts"])
        plain1, fall1 = self._counters()
        assert plain1 == plain0 and fall1 > fall0  # rode the pyarrow path
        want = shard.read_row_group(ctx, 0, columns=["u32", "d32", "ts"])
        for c in ("u32", "d32", "ts"):
            ref = np.ascontiguousarray(
                want[c].to_numpy(zero_copy_only=False))
            assert got[c].dtype == ref.dtype
            np.testing.assert_array_equal(got[c], ref)
        assert got["u32"][0] == 2147483653  # not -2147483643
        assert got["d32"].dtype.kind == "M"  # datetime64, not raw int32

    def test_signed_int_annotation_stays_fast(self, ctx, tmp_path, rng):
        """An explicit INT(32, signed)/INT(64, signed) annotation is exactly
        the physical meaning: must stay eligible (no over-conservative
        fallback for what common writers emit)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from strom.formats.parquet import ParquetShard

        n = 5000
        table = pa.table({
            "i32": pa.array(rng.integers(-(1 << 20), 1 << 20, n,
                                         dtype=np.int32)),
            "i64": pa.array(rng.integers(-(1 << 40), 1 << 40, n,
                                         dtype=np.int64)),
        })
        p = str(tmp_path / "signed.parquet")
        pq.write_table(table, p, compression="NONE", use_dictionary=False)
        shard = ParquetShard(p, ctx=ctx)
        plain0, fall0 = self._counters()
        got = shard.read_row_group_arrays(ctx, 0, ["i32", "i64"])
        plain1, fall1 = self._counters()
        assert plain1 > plain0 and fall1 == fall0
        for c in ("i32", "i64"):
            np.testing.assert_array_equal(got[c], table[c].to_numpy())

    def test_wide_def_levels_fall_back(self, ctx, tmp_path, rng):
        """max_definition_level > 1 (optional leaf in an optional group):
        _defs_all_present only parses bit-width-1 blocks, so the decoder
        must refuse BEFORE parsing instead of staying conservative by
        coincidence (ADVICE.md r5 low)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from strom.formats.parquet import (ParquetShard,
                                           _PlainDecodeUnsupported,
                                           decode_plain_pages)

        n = 2000
        table = pa.table({"s": pa.array(
            [{"v": float(i)} for i in range(n)],
            type=pa.struct([("v", pa.float64())]))})
        p = str(tmp_path / "nested_def.parquet")
        pq.write_table(table, p, compression="NONE", use_dictionary=False,
                       write_statistics=False)
        shard = ParquetShard(p, ctx=ctx)
        ci = shard._col_indices(["s.v"])[0]
        cs = shard.metadata.schema.column(ci)
        assert cs.max_definition_level > 1  # the case under test
        buf = ctx.pread(shard.column_chunk_extents(0, ["s.v"]))
        with pytest.raises(_PlainDecodeUnsupported):
            decode_plain_pages(shard.metadata.row_group(0).column(ci), cs,
                               buf)

    def test_thrift_skip_bool_list_elements(self):
        """list<bool> elements are ONE BYTE each in thrift compact (unlike
        bool struct fields, whose value rides the type nibble): the skip
        walk must advance size bytes or it desynchronizes (ADVICE.md r5)."""
        from strom.formats.parquet import _thrift_struct

        buf = bytes([
            0x19, 0x31,        # field 1: list, 3 bool elements
            0x01, 0x02, 0x01,  # one byte per element
            0x25, 0x2A,        # field 3: i32 zigzag -> 21
            0x00,              # stop
        ])
        out, pos = _thrift_struct(memoryview(buf), 0)
        assert out[3] == 21  # landed on the field AFTER the list
        assert pos == len(buf)


class TestWdsStriped:
    """WDS shards on a RAID0 striped set (BASELINE config #3's '4×NVMe
    RAID0'): index through SourceIO, payload gathers stripe-decode in the
    delivery layer via the registered path alias."""

    def test_striped_shard_index_and_payload(self, ctx, tmp_path, rng):
        from strom.engine.raid0 import stripe_file

        plain = str(tmp_path / "plain.tar")
        payloads = [(f"s{i:02d}", {"jpg": rng.bytes(3000 + 217 * i),
                                   "cls": str(i % 7).encode()})
                    for i in range(6)]
        make_wds_shard(plain, payloads)
        members = [str(tmp_path / f"wm{i}.bin") for i in range(4)]
        stripe_file(plain, members, 8192)
        virt = str(tmp_path / "striped.tar")  # not on disk
        ctx.register_striped(virt, members, 8192)

        ss = WdsShardSet([virt], ctx=ctx)
        ref = WdsShardSet([plain])
        assert [s.key for s in ss] == [s.key for s in ref]
        for (key, members_), sample in zip(payloads, ss):
            got = ctx.pread(sample.extents(["jpg", "cls"]))
            assert got.tobytes() == members_["jpg"] + members_["cls"]

    def test_striped_vision_pipeline(self, tmp_path, rng):
        """End-to-end config #3 shape on the fake mesh: JPEG WDS shard on a
        striped set -> batch-sharded image arrays."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        cv2 = pytest.importorskip("cv2")
        from strom.engine.raid0 import stripe_file
        from strom.pipelines import make_vit_wds_pipeline

        plain = str(tmp_path / "v.tar")
        samples = []
        for i in range(16):
            img = rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)
            ok, buf = cv2.imencode(".jpg", img)
            assert ok
            samples.append((f"s{i:03d}", {"jpg": buf.tobytes(),
                                          "cls": str(i % 5).encode()}))
        make_wds_shard(plain, samples)
        members = [str(tmp_path / f"vm{i}.bin") for i in range(4)]
        stripe_file(plain, members, 16384)
        virt = str(tmp_path / "v_striped.tar")
        c = StromContext(StromConfig(engine="python", queue_depth=8,
                                     num_buffers=8))
        try:
            c.register_striped(virt, members, 16384)
            mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
            sharding = NamedSharding(mesh, P("dp", None, None, None))
            with make_vit_wds_pipeline(c, [virt], batch=8, image_size=32,
                                       sharding=sharding,
                                       decode_workers=2) as pipe:
                imgs, lbls = next(pipe)
                assert imgs.shape == (8, 32, 32, 3)
                assert imgs.dtype == np.uint8
                assert int(np.asarray(lbls).max()) < 5
        finally:
            c.close()


class TestParquetStriped:
    def test_striped_parquet_roundtrip(self, ctx, tmp_path, rng):
        """A Parquet file on a RAID0 striped set: metadata, footer, and
        column-chunk gathers all resolve through the path alias (stripe_file
        zero-pads the tail, so the alias carries the TRUE size — the footer
        must sit at the real EOF)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from strom.engine.raid0 import stripe_file
        from strom.formats.parquet import ParquetShard

        n = 5_000
        table = pa.table({
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "value": pa.array(rng.normal(size=n)),
        })
        plain = str(tmp_path / "plain.parquet")
        pq.write_table(table, plain, row_group_size=1250, compression="zstd")
        members = [str(tmp_path / f"pm{i}.bin") for i in range(3)]
        stripe_file(plain, members, 32768)
        virt = str(tmp_path / "striped.parquet")
        ctx.register_striped(virt, members, 32768,
                             size=os.path.getsize(plain))

        shard = ParquetShard(virt, ctx=ctx)
        assert shard.num_rows == n
        parts = [shard.read_row_group(ctx, g, columns=["id", "value"])
                 for g in range(shard.num_row_groups)]
        got = pa.concat_tables(parts)
        assert got.equals(table.select(["id", "value"]))
