"""Kill/restart recovery harness (ISSUE 14 tentpole, front 3): the tier-1
acceptance — SIGKILL at a seeded mid-epoch step, restart from
last_committed + StepToken, remaining batch stream bit-identical, no epoch
replay, no orphaned tmp checkpoint — plus a FaultRule op-window over the
kill step and the SIGTERM variant."""

import json

import pytest

from strom.ckpt.jobstate import RESUME_FIELDS
from strom.faults.resume_harness import run_kill_resume

pytest.importorskip("jax")


def _assert_contract(out: dict) -> None:
    assert out["failures"] == [], f"harness contract broke: {out['failures']}"
    assert out["resume_ok"] == 1
    # mid-epoch kill, restart strictly inside the epoch — no epoch replay
    assert 0 < out["resume_restart_step"] <= out["resume_kill_step"] + 1
    # only the un-checkpointed tail re-ran
    assert 0 <= out["resume_replayed_batches"] <= 8
    assert out["resume_batches_checked"] > 0
    # the full verdict column set is present (bench copy-loop contract)
    assert set(RESUME_FIELDS) <= set(out)


class TestKillResume:
    def test_sigkill_mid_epoch_bit_identical_resume(self, tmp_path):
        """The ISSUE 14 acceptance: SIGKILL at a seeded mid-epoch step →
        restart from last_committed + its StepToken → remaining batch
        stream bit-identical to an uninterrupted run, final train state
        equal, no orphaned tmp checkpoint."""
        out = run_kill_resume(str(tmp_path), seed=1)
        _assert_contract(out)
        # an async commit was very likely mid-flight at SIGKILL at least
        # once across the suite; whatever orphan it left was swept
        assert out["resume_orphan_tmps"] >= 0

    def test_fault_rule_op_window_over_kill_step(self, tmp_path):
        """ISSUE 14 satellite: a FaultRule op-window of transient read
        faults spanning the ops around the seeded kill/restart region —
        retries absorb them and the resume contract still holds."""
        # probability-based rules, NOT `every`: the match counter is
        # shared across the concurrently-pipelined op stream, so with
        # `every` an op's whole retry chain can land on matched counts
        # (~1/N per retry — a few-percent flake). With p, a retry chain
        # only exhausts at p^retries (~1e-4 here): the contract stays
        # "retries absorb the window", not "the seed got lucky".
        plan = json.dumps({"seed": 4, "rules": [
            {"kind": "errno", "op": "read", "op_lo": 8, "op_hi": 160,
             "p": 0.05, "times": 6, "err": "EIO"},
            {"kind": "short_read", "op": "read", "op_lo": 8, "op_hi": 160,
             "p": 0.05, "times": 6, "short_frac": 0.5},
            {"kind": "latency", "op": "read", "op_lo": 8, "op_hi": 160,
             "p": 0.2, "times": 20, "latency_s": 0.002},
        ]})
        out = run_kill_resume(str(tmp_path), seed=2, fault_plan=plan)
        _assert_contract(out)

    @pytest.mark.slow
    def test_sigterm_variant(self, tmp_path):
        out = run_kill_resume(str(tmp_path), seed=3, sig="TERM")
        _assert_contract(out)

    @pytest.mark.slow
    def test_warm_hints_travel_with_the_token(self, tmp_path):
        """With a hot cache + warm hints on, the resumed process replays
        the dead process's cache manifest (resume_warm_bytes > 0)."""
        out = run_kill_resume(str(tmp_path), seed=5, warm_hints=True,
                              cache_bytes=4 << 20)
        _assert_contract(out)
        assert (out.get("resume_warm_bytes") or 0) > 0
