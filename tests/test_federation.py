"""Cluster observability plane (ISSUE 18): federation + fleet health.

Covers the acceptance invariants directly:

- merge math: the cluster aggregate equals the element-wise SUM of the
  per-host snapshots (counters, gauges, histogram buckets), with
  percentiles RE-DERIVED from the merged buckets (never averaged);
- missing-host tolerance: a host lacking a key simply doesn't contribute;
- stale ageing: a host whose scrape stops lands unhealthy after
  ``stale_s`` and its last counters drop out of the aggregate;
- progress-stall watchdog: a host that scrapes fine but whose progress
  counters stop advancing flips unhealthy (hung-but-listening);
- the unhealthy transition fires the remote flight trigger ONCE and
  dumps a host-stamped local bundle;
- a live 2-context ``/cluster`` route serves per-host rows + the summed
  aggregate with ``cluster_hosts_unhealthy == 0``;
- a 2-process subprocess run leaves per-host trace files whose merge
  carries cross-host flow-linked peer-fetch spans under one req id.
"""

import glob
import json
import os
import time
import urllib.request

import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.obs.federation import ClusterView, FED_FIELDS, merge_snapshots
from strom.utils.stats import _Histogram, percentile_from_buckets


def _hist_snap(stem, buckets, total_us):
    """One histogram's registry-snapshot keys (stats.snapshot scheme)."""
    h = _Histogram()
    h.add_buckets(buckets, total_us)
    return {f"{stem}_hist": list(h.buckets), f"{stem}_count": h.count,
            f"{stem}_total_us": h.total_us, f"{stem}_mean_us": h.mean_us,
            f"{stem}_p50_us": h.percentile(0.50),
            f"{stem}_p99_us": h.percentile(0.99)}


def _synth(reads, burning, buckets, total_us):
    snap = {"engine_reads": reads, "slo_burning": burning}
    snap.update(_hist_snap("lat", buckets, total_us))
    return snap


# -- merge math ---------------------------------------------------------------

class TestMergeSnapshots:
    def test_aggregate_equals_sum(self):
        """3 synthetic hosts: every counter/gauge sums, histogram buckets
        merge element-wise, and count/total follow."""
        b1 = [0] * 24
        b1[3], b1[10] = 5, 2
        b2 = [0] * 24
        b2[3], b2[20] = 1, 1
        b3 = [0] * 24
        b3[0] = 7
        snaps = {"a": _synth(10, True, b1, 900.0),
                 "b": _synth(32, False, b2, 5000.0),
                 "c": _synth(0, False, b3, 70.0)}
        agg = merge_snapshots(snaps)
        assert agg["engine_reads"] == 42
        assert agg["slo_burning"] == 1  # bools sum as int
        assert agg["lat_hist"] == [x + y + z
                                   for x, y, z in zip(b1, b2, b3)]
        assert agg["lat_count"] == sum(b1) + sum(b2) + sum(b3)
        assert agg["lat_total_us"] == pytest.approx(5970.0)

    def test_percentiles_rederived_not_summed(self):
        """The merged p99 must come from the merged buckets — a sum (or
        average) of per-host p99s is not a percentile of anything."""
        lo = [0] * 24
        lo[2] = 100  # 100 obs in [4, 8) us
        hi = [0] * 24
        hi[12] = 1  # 1 obs in [4096, 8192) us
        snaps = {"a": _synth(0, False, lo, 600.0),
                 "b": _synth(0, False, hi, 5000.0)}
        agg = merge_snapshots(snaps)
        merged = [x + y for x, y in zip(lo, hi)]
        assert agg["lat_p99_us"] == percentile_from_buckets(merged, 0.99)
        assert agg["lat_p99_us"] != snaps["a"]["lat_p99_us"] + \
            snaps["b"]["lat_p99_us"]
        # mean re-derived from merged totals, not averaged
        assert agg["lat_mean_us"] == pytest.approx(5600.0 / 101)

    def test_missing_host_tolerance(self):
        """A host lacking a key (or the histogram) contributes nothing for
        it; the others still sum."""
        b = [0] * 24
        b[5] = 3
        snaps = {"a": _synth(7, False, b, 100.0),
                 "b": {"engine_reads": 5},  # no histogram at all
                 "c": {"other_counter": 2.5}}
        agg = merge_snapshots(snaps)
        assert agg["engine_reads"] == 12
        assert agg["other_counter"] == 2.5
        assert agg["lat_count"] == 3
        assert merge_snapshots({}) == {}

    def test_non_numeric_leaves_dropped(self):
        agg = merge_snapshots({"a": {"name": "worker-a", "n": 1},
                               "b": {"name": "worker-b", "n": 2}})
        assert agg == {"n": 3}


# -- ClusterView health machine (injected fetch/flight, no sockets) ----------

def _snapshot_doc(*, serves=0, traced=0, goodput=97.5, progress=0):
    return {"sections": {"dist": {"peer_serves": serves,
                                  "peer_serves_traced": traced,
                                  "peer_hits": 3, "peer_misses": 1},
                         "steps": {"goodput_pct": goodput}},
            "global": {"ssd2tpu_bytes": progress,
                       "sched_queue_wait_p99_us": 128.0,
                       "slo_burning": 0}}


class TestClusterView:
    def _view(self, hosts, fetch, **kw):
        kw.setdefault("publish", False)
        kw.setdefault("start", False)
        return ClusterView(hosts, fetch_fn=fetch, **kw)

    def test_fields_and_rows(self):
        docs = {"h0:1": _snapshot_doc(serves=10, traced=8, progress=100),
                "h1:1": _snapshot_doc(serves=10, traced=2, progress=50)}
        view = self._view({"h0": "h0:1", "h1": "h1:1"},
                          lambda addr: docs[addr])
        view.poll_now()
        st = view.stats()
        assert set(st) == set(FED_FIELDS)
        assert st["cluster_hosts"] == 2
        assert st["cluster_hosts_unhealthy"] == 0
        assert st["cluster_trace_linked_ratio"] == 0.5
        assert st["cluster_scrape_lag_p99_us"] > 0
        doc = view.snapshot()
        row = doc["hosts"]["h0"]
        assert row["addr"] == "h0:1" and row["healthy"]
        assert row["goodput_pct"] == 97.5
        assert row["peer_hit_ratio"] == 0.75
        assert row["sched_queue_wait_p99_us"] == 128.0
        # aggregate == sum of the per-host globals
        assert doc["aggregate"]["ssd2tpu_bytes"] == 150
        view.close()

    def test_stale_host_ages_out_and_fires_flight_once(self):
        alive = {"ok": True}
        flights, dumps = [], []

        class Rec:
            def dump(self, reason, note=""):
                dumps.append((reason, note))

        def fetch(addr):
            if addr == "bad:1" and not alive["ok"]:
                raise OSError("connection refused")
            return _snapshot_doc(progress=7)

        view = self._view({"good": "good:1", "bad": "bad:1"}, fetch,
                          flight_fn=flights.append, recorder=Rec(),
                          stale_s=0.08, stall_s=60.0)
        view.poll_now()
        assert view.stats()["cluster_hosts_unhealthy"] == 0
        alive["ok"] = False
        time.sleep(0.12)
        view.poll_now()
        view.poll_now()  # still unhealthy: must NOT fire again
        st = view.stats()
        assert st["cluster_hosts_unhealthy"] == 1
        assert flights == ["bad:1"]
        assert dumps == [("cluster_unhealthy", "host=bad")]
        # the dead host's last counters are OUT of the aggregate
        assert view.snapshot()["aggregate"]["ssd2tpu_bytes"] == 7
        # recovery re-arms the one-shot
        alive["ok"] = True
        view.poll_now()
        assert view.stats()["cluster_hosts_unhealthy"] == 0
        alive["ok"] = False
        time.sleep(0.12)
        view.poll_now()
        assert flights == ["bad:1", "bad:1"]
        view.close()

    def test_progress_stall_flags_unhealthy(self):
        """Scrapes keep succeeding but the progress counters never move:
        hung-but-listening must flip unhealthy after stall_s."""
        view = self._view({"h": "h:1"},
                          lambda a: _snapshot_doc(progress=42),
                          stale_s=60.0, stall_s=0.08)
        view.poll_now()
        assert view.stats()["cluster_hosts_unhealthy"] == 0
        time.sleep(0.12)
        view.poll_now()
        assert view.stats()["cluster_hosts_unhealthy"] == 1
        view.close()

    def test_never_scraped_grace_then_unhealthy(self):
        def fetch(addr):
            raise OSError("down from the start")

        view = self._view({"h": "h:1"}, fetch, stale_s=0.08)
        view.poll_now()
        assert view.stats()["cluster_hosts_unhealthy"] == 0  # grace
        time.sleep(0.12)
        view.poll_now()
        assert view.stats()["cluster_hosts_unhealthy"] == 1
        view.close()


# -- live /cluster over two real contexts -------------------------------------

def test_cluster_route_live_two_contexts(tmp_path):
    """Two StromContexts in one process, each serving /stats; the first
    attaches a ClusterView over both and serves /cluster: per-host rows,
    aggregate == sum of the scraped globals, zero unhealthy hosts."""
    cfg = StromConfig(engine="python", queue_depth=4, num_buffers=4)
    ctx0 = StromContext(cfg, metrics_port=0)
    ctx1 = StromContext(cfg, metrics_port=0)
    try:
        addrs = {f"h{i}": f"127.0.0.1:{c.metrics_server.port}"
                 for i, c in enumerate((ctx0, ctx1))}
        view = ctx0.attach_cluster(addrs, interval_s=0.1, publish=False)
        assert ctx0.cluster_view is view
        view.poll_now()
        globals_ = {}
        for h, a in addrs.items():
            with urllib.request.urlopen(f"http://{a}/stats?sections=dist",
                                        timeout=10) as r:
                globals_[h] = json.loads(r.read())["global"]
        with urllib.request.urlopen(
                f"http://{addrs['h0']}/cluster", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["cluster_hosts"] == 2
        assert doc["cluster_hosts_unhealthy"] == 0
        assert set(doc["hosts"]) == {"h0", "h1"}
        assert all(row["healthy"] for row in doc["hosts"].values())
        # the aggregate is the SUM of the per-host global snapshots (both
        # contexts share one process-global registry, so h0 == h1 and the
        # aggregate is exactly 2x — the invariant is still sum-of-parts)
        expect = merge_snapshots(globals_)
        for k in ("events_dropped",):
            doc["aggregate"].pop(k, None)
            expect.pop(k, None)
        for k, v in expect.items():
            assert doc["aggregate"].get(k) == pytest.approx(v), k
    finally:
        ctx0.close()
        ctx1.close()
    # a context without attach_cluster 404s the route
    ctx = StromContext(cfg, metrics_port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{ctx.metrics_server.port}/cluster",
                timeout=10)
        assert ei.value.code == 404
    finally:
        ctx.close()


def test_unhealthy_host_leaves_stamped_bundle(tmp_path):
    """Killing a worker flips cluster_hosts_unhealthy to 1 and the
    coordinator dumps a flight bundle whose manifest carries the host
    stamp + peer addresses (the fleet-attribution contract)."""
    fdir = str(tmp_path / "fl")
    cfg0 = StromConfig(engine="python", queue_depth=4, num_buffers=4,
                       flight_dir=fdir)
    cfg1 = StromConfig(engine="python", queue_depth=4, num_buffers=4)
    ctx0 = StromContext(cfg0, metrics_port=0)
    ctx1 = StromContext(cfg1, metrics_port=0)
    killed = False
    try:
        view = ctx0.attach_cluster(
            {"h0": f"127.0.0.1:{ctx0.metrics_server.port}",
             "h1": f"127.0.0.1:{ctx1.metrics_server.port}"},
            interval_s=0.1, stale_s=0.3, publish=False, start=False)
        view.poll_now()
        assert view.stats()["cluster_hosts_unhealthy"] == 0
        ctx1.close()  # the "kill"
        killed = True
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            view.poll_now()
            if view.stats()["cluster_hosts_unhealthy"] == 1:
                break
            time.sleep(0.1)
        assert view.stats()["cluster_hosts_unhealthy"] == 1
        bundles = sorted(glob.glob(os.path.join(fdir, "flight-*")))
        assert bundles, "unhealthy transition left no local bundle"
        from strom.obs.flight import load_bundle
        man = load_bundle(bundles[-1])["manifest"]
        assert man["reason"] == "cluster_unhealthy"
        assert man["note"] == "host=h1"
        assert man["host"] and ":" in man["host"]  # hostname:pid
        assert isinstance(man["peer_addrs"], list)
    finally:
        ctx0.close()
        if not killed:
            ctx1.close()


# -- 2-process run: merged trace with cross-host flow-linked spans ------------

def test_two_proc_merged_trace_links_hosts(tmp_path):
    """The acceptance trace artifact: a 2-process dist run leaves
    trace_<rank>.json per host; merged, the peer fetches appear as ONE
    reqx flow chain per fetch — client 's'+'f' on the asking host,
    server 't' spans on the serving host, all billing the same req id —
    and rank 0's result carries the FED fields with zero unhealthy."""
    from strom.dist.launch import launch_local, make_fixture
    from strom.obs.chrome_trace import load_events, merge_host_traces

    data = str(tmp_path / "data")
    make_fixture(data, files=4, records=48, seq_len=16)
    run = str(tmp_path / "run")
    results = launch_local(2, data, run, steps=4, batch=8, seq_len=16)
    for r, res in enumerate(results):
        assert res.get("rc") == 0 and res.get("ok"), \
            f"worker {r}: {res.get('tail', res)}"
    # rank 0 federated the fleet during the run
    r0 = results[0]
    assert r0["cluster_hosts"] == 2
    assert r0["cluster_hosts_unhealthy"] == 0
    assert r0["cluster_trace_linked_ratio"] > 0
    host_events = {}
    for rank in (0, 1):
        path = os.path.join(run, f"trace_{rank}.json")
        assert os.path.exists(path), f"worker {rank} left no trace"
        host_events[f"rank{rank}"] = load_events(path)

    # per-flow census: phases seen per host for every reqx chain
    flows: dict = {}
    for host, evs in host_events.items():
        for e in evs:
            if e.get("cat") == "reqx" and e.get("ph") in ("s", "t", "f"):
                flows.setdefault(e["id"], {}).setdefault(host, set()) \
                    .add(e["ph"])
    linked = {fid: by_host for fid, by_host in flows.items()
              if len(by_host) >= 2}
    assert linked, "no cross-host flow-linked peer fetch in the traces"
    fid, by_host = next(iter(linked.items()))
    client = next(h for h, ps in by_host.items() if "s" in ps)
    server = next(h for h, ps in by_host.items() if "t" in ps)
    assert client != server
    # both sides billed the same request id: the client's peer.fetch span
    # carries args.flow == fid and args.req; the server's spans (bound to
    # the same flow) carry the SAME args.req
    fetch = next(e for e in host_events[client]
                 if e.get("name") == "peer.fetch"
                 and (e.get("args") or {}).get("flow") == fid)
    rid = fetch["args"]["req"]
    srv_spans = [e for e in host_events[server] if e.get("ph") == "X"
                 and (e.get("args") or {}).get("req") == rid]
    assert {"peer.queue", "peer.grant", "peer.send"} <= \
        {e["name"] for e in srv_spans}, srv_spans
    # the merged document keeps both hosts as process rows and the flow
    # events on both sides of the arrow
    doc = merge_host_traces(host_events)
    pids = {te["pid"] for te in doc["traceEvents"]
            if te.get("cat") == "reqx" and te.get("id") == fid}
    assert len(pids) == 2, "merged flow chain lost a side"
    assert set(doc["otherData"]["clock_shifts_us"]) == {"rank0", "rank1"}


def test_fed_fields_lift_into_measure_ingest(tmp_path):
    """measure_ingest folds rank 0's federation gauges into the bench
    columns (the dist arm's copy source)."""
    from strom.dist.launch import measure_ingest

    res = measure_ingest(2, str(tmp_path), steps=3, batch=8, seq_len=16)
    assert res["dist_ok"] == 1
    for k in FED_FIELDS:
        assert k in res, k
    assert res["cluster_hosts"] == 2
    assert res["cluster_hosts_unhealthy"] == 0
