"""Flagship model + sharded train step + multichip dryrun (fake 8-dev mesh)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from strom.models.llama import (LlamaConfig, forward, init_params,
                                next_token_loss)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_forward_shapes_finite(tiny):
    cfg, params = tiny
    tokens = jnp.array(np.random.default_rng(0).integers(0, cfg.vocab, (2, 16)),
                       dtype=jnp.int32)
    logits = forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_causality(tiny):
    """Changing a future token must not change past logits."""
    cfg, params = tiny
    rng = np.random.default_rng(1)
    t1 = rng.integers(0, cfg.vocab, (1, 16)).astype(np.int32)
    t2 = t1.copy()
    t2[0, 10:] = (t2[0, 10:] + 7) % cfg.vocab
    l1 = forward(params, jnp.array(t1), cfg)
    l2 = forward(params, jnp.array(t2), cfg)
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               rtol=1e-4, atol=1e-4)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_loss_decreases_overfitting(tiny):
    cfg, _ = tiny
    import optax

    from strom.parallel.mesh import make_mesh
    from strom.parallel.train import init_train_state, make_optimizer, make_train_step

    mesh = make_mesh({"dp": 2, "tp": 4})
    opt = make_optimizer(lr=1e-2, warmup=1)
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
    step = make_train_step(cfg, mesh, opt)
    tokens = jnp.array(np.random.default_rng(2).integers(0, cfg.vocab, (4, 33)),
                       dtype=jnp.int32)
    losses = []
    for _ in range(5):
        state, metrics = step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 5


def test_param_count_matches():
    cfg = LlamaConfig.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    actual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert actual == cfg.param_count()


def test_param_shardings_cover_all_leaves(tiny):
    from jax.sharding import PartitionSpec as P

    from strom.parallel.sharding import param_specs

    cfg, params = tiny
    specs = param_specs(params)
    leaves = jax.tree.leaves(params)
    spec_flat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    assert len(leaves) == len(spec_flat)
    # tp must shard every matmul weight
    matmul_names = {"embed", "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                    "lm_head"}
    for path, spec in spec_flat:
        name = path[-1].key
        if name in matmul_names:
            assert any(ax == "tp" for ax in spec), (name, spec)
        else:
            assert name in {"attn_norm", "mlp_norm", "final_norm"}


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


@pytest.mark.slow
def test_lower_at_scale_subprocess():
    """The 16/32-device lowering the driver's dryrun spawns (VERDICT.md r4
    next #5) — run the exact subprocess here so a regression surfaces in
    the suite, not first in the round artifact. conftest sets
    STROM_DRYRUN_AT_SCALE=0 precisely so the dryrun test above does NOT
    pay this cost twice; this test is the single, explicit payer."""
    import os
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, "-m", "strom.parallel.dryrun", "--lower-at-scale"],
        capture_output=True, text=True, timeout=900, cwd=repo_root)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "16 devices" in res.stdout, res.stdout
    assert "32 devices" in res.stdout, res.stdout


def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert bool(jnp.isfinite(out).all())
