"""T5 pipelines: sampler checkpointing, llama/vision loaders on the fake
8-device mesh, parquet scan fan-out (SURVEY.md §4.2 'Device delivery' and
'Overlap/0-stall' rows)."""

import os

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.parallel.mesh import make_mesh
from strom.pipelines.sampler import (EpochShuffleSampler, SamplerState,
                                     dataset_fingerprint, load_loader_state,
                                     save_loader_state)


@pytest.fixture(scope="module")
def ctx():
    c = StromContext(StromConfig(engine="python", queue_depth=8, num_buffers=8))
    yield c
    c.close()


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"dp": 8}, devices=jax.devices()[:8])


# ---------------------------------------------------------------- sampler
class TestSampler:
    def test_covers_epoch_exactly(self):
        s = EpochShuffleSampler(100, 10, seed=1)
        it = iter(s)
        seen = np.concatenate([next(it) for _ in range(10)])
        assert sorted(seen) == list(range(100))

    def test_deterministic_and_reshuffled(self):
        a = [next(iter(EpochShuffleSampler(50, 50, seed=7))) for _ in range(1)][0]
        b = next(iter(EpochShuffleSampler(50, 50, seed=7)))
        np.testing.assert_array_equal(a, b)
        it = iter(EpochShuffleSampler(50, 50, seed=7))
        e0, e1 = next(it), next(it)
        assert not np.array_equal(e0, e1)  # epoch 1 reshuffles
        np.testing.assert_array_equal(sorted(e0), sorted(e1))

    def test_resume_mid_epoch(self):
        s1 = EpochShuffleSampler(100, 10, seed=3)
        it1 = iter(s1)
        for _ in range(13):
            next(it1)
        resumed = EpochShuffleSampler(
            100, 10, seed=3,
            state=SamplerState(epoch=1, batch_in_epoch=3, seed=3))
        np.testing.assert_array_equal(next(iter(resumed)), next(it1))

    def test_state_file_roundtrip(self, tmp_path, data_file):
        path, _ = data_file
        fp = dataset_fingerprint((path,))
        st = SamplerState(epoch=2, batch_in_epoch=5, seed=9)
        f = str(tmp_path / "loader.json")
        save_loader_state(f, st, fp, {"k": 1})
        got, extra = load_loader_state(f, fp)
        assert got == st and extra == {"k": 1}
        with pytest.raises(ValueError, match="different dataset"):
            load_loader_state(f, {"paths": ["other"], "sizes": [1]})

    def test_ragged_rejected(self):
        with pytest.raises(ValueError, match="drop_last"):
            EpochShuffleSampler(10, 3, drop_last=False)


# ---------------------------------------------------------- llama pipeline
class TestLlamaPipeline:
    @pytest.fixture(scope="class")
    def token_shards(self, tmp_path_factory):
        rng = np.random.default_rng(11)
        td = tmp_path_factory.mktemp("tokens")
        paths, golden = [], []
        seq = 16  # record = 17 tokens
        for i in range(3):
            # ids < LlamaConfig.tiny().vocab so batches feed the train step
            t = rng.integers(0, 500, 17 * 20 + 5, dtype=np.int32)  # 20 rec + tail
            p = str(td / f"shard{i}.bin")
            t.tofile(p)
            paths.append(p)
            golden.append(t[: 17 * 20].reshape(20, 17))
        return paths, np.concatenate(golden), seq

    def test_sequential_content_golden(self, ctx, mesh, token_shards):
        from strom.pipelines import make_llama_pipeline

        paths, golden, seq = token_shards
        sharding = NamedSharding(mesh, P("dp", None))
        with make_llama_pipeline(ctx, paths, batch=8, seq_len=seq,
                                 sharding=sharding, shuffle=False) as pipe:
            b0 = next(pipe)
            assert b0.shape == (8, 17) and b0.sharding == sharding
            np.testing.assert_array_equal(np.asarray(b0), golden[:8])
            np.testing.assert_array_equal(np.asarray(next(pipe)), golden[8:16])

    def test_shuffled_epoch_covers_all(self, ctx, mesh, token_shards):
        from strom.pipelines import make_llama_pipeline

        paths, golden, seq = token_shards
        # 60 records don't split over 8 devices: replicate (also exercises the
        # planner's read-once-put-everywhere dedupe)
        sharding = NamedSharding(mesh, P(None, None))
        with make_llama_pipeline(ctx, paths, batch=60, seq_len=seq,
                                 sharding=sharding, seed=5) as pipe:
            batch = np.asarray(next(pipe))
        # one full epoch in one batch: same records, different order
        assert not np.array_equal(batch, golden)
        np.testing.assert_array_equal(
            batch[np.lexsort(batch.T[::-1])], golden[np.lexsort(golden.T[::-1])])

    def test_checkpoint_resume_replays_nothing(self, ctx, mesh, token_shards,
                                               tmp_path):
        from strom.pipelines import make_llama_pipeline

        paths, _, seq = token_shards
        sharding = NamedSharding(mesh, P("dp", None))
        f = str(tmp_path / "loader.json")
        with make_llama_pipeline(ctx, paths, batch=8, seq_len=seq,
                                 sharding=sharding, seed=13,
                                 prefetch_depth=3) as pipe:
            for _ in range(3):
                next(pipe)
            pipe.save_state(f)  # prefetcher has run ahead; state must not
            want_next = np.asarray(next(pipe))
        with make_llama_pipeline(ctx, paths, batch=8, seq_len=seq,
                                 sharding=sharding, seed=13,
                                 resume_from=f) as pipe2:
            np.testing.assert_array_equal(np.asarray(next(pipe2)), want_next)

    def test_resume_with_wrong_seed_rejected(self, ctx, mesh, token_shards,
                                             tmp_path):
        from strom.pipelines import make_llama_pipeline

        paths, _, seq = token_shards
        sharding = NamedSharding(mesh, P("dp", None))
        f = str(tmp_path / "loader.json")
        with make_llama_pipeline(ctx, paths, batch=8, seq_len=seq,
                                 sharding=sharding, seed=13) as pipe:
            next(pipe)
            pipe.save_state(f)
        with pytest.raises(ValueError, match="seed 13"):
            make_llama_pipeline(ctx, paths, batch=8, seq_len=seq,
                                sharding=sharding, seed=7, resume_from=f)

    def test_feeds_train_step(self, ctx, mesh, token_shards):
        from strom.models.llama import LlamaConfig
        from strom.parallel.train import (init_train_state, make_optimizer,
                                          make_train_step)
        from strom.pipelines import make_llama_pipeline

        paths, _, seq = token_shards
        tmesh = make_mesh({"dp": 2, "tp": 4}, devices=jax.devices()[:8])
        cfg = LlamaConfig.tiny()
        opt = make_optimizer()
        state = init_train_state(jax.random.PRNGKey(0), cfg, tmesh, opt)
        step = make_train_step(cfg, tmesh, opt)
        with make_llama_pipeline(ctx, paths, batch=8, seq_len=seq,
                                 sharding=NamedSharding(tmesh, P("dp", None))) as pipe:
            for _ in range(2):
                state, metrics = step(state, next(pipe))
        assert np.isfinite(float(metrics["loss"]))
        assert int(state.step) == 2


class TestEpochSync:
    def test_barrier_gates_dispatch(self, monkeypatch):
        """The epoch barrier must fire BEFORE the first next-epoch batch is
        dispatched (the sampler runs ahead of consumption, so a consumer-side
        barrier would let next-epoch I/O start early)."""
        import strom.parallel.multihost as mh
        from strom.pipelines.base import Pipeline

        events = []
        monkeypatch.setattr(mh, "epoch_barrier",
                            lambda name: events.append(("barrier", name)))
        sampler = EpochShuffleSampler(8, 4, seed=0)  # 2 batches/epoch

        def make_batch(idx, serial):
            events.append(("batch", serial))
            return serial

        pipe = Pipeline(sampler, make_batch, depth=1, epoch_sync=True)
        assert [next(pipe) for _ in range(4)] == [0, 1, 2, 3]
        pipe.close()
        # the epoch-1 barrier is appended on the consumer thread before the
        # serial-2 thunk is even submitted to the executor
        bi = events.index(("barrier", "strom-epoch-1"))
        b2 = events.index(("batch", 2))
        assert bi < b2, events


# --------------------------------------------------------- vision pipeline
class TestVisionPipeline:
    @pytest.fixture(scope="class")
    def wds_shards(self, tmp_path_factory):
        import cv2

        from tests.test_formats import make_wds_shard

        rng = np.random.default_rng(21)
        td = tmp_path_factory.mktemp("wds")
        paths = []
        labels = {}
        k = 0
        for s in range(2):
            samples = []
            for i in range(12):
                img = rng.integers(0, 256, (40 + i, 50, 3), dtype=np.uint8)
                ok, buf = cv2.imencode(".jpg", img)
                assert ok
                samples.append((f"s{k:04d}", {"jpg": buf.tobytes(),
                                              "cls": str(k % 10).encode()}))
                labels[f"s{k:04d}"] = k % 10
                k += 1
            p = str(td / f"wds{s}.tar")
            make_wds_shard(p, samples)
            paths.append(p)
        return paths, labels

    def test_batch_shapes_and_labels(self, ctx, mesh, wds_shards):
        from strom.pipelines import make_imagenet_resnet_pipeline

        paths, labels = wds_shards
        sharding = NamedSharding(mesh, P("dp", None, None, None))
        with make_imagenet_resnet_pipeline(
                ctx, paths, batch=8, image_size=32, sharding=sharding,
                shuffle=False, decode_workers=2) as pipe:
            imgs, lbls = next(pipe)
        assert imgs.shape == (8, 32, 32, 3) and imgs.dtype == np.uint8
        assert imgs.sharding == sharding
        assert lbls.shape == (8,)
        np.testing.assert_array_equal(np.asarray(lbls),
                                      [labels[f"s{i:04d}"] for i in range(8)])

    def test_deterministic_augmentation(self, ctx, mesh, wds_shards):
        from strom.pipelines import make_vit_wds_pipeline

        paths, _ = wds_shards
        sharding = NamedSharding(mesh, P("dp", None, None, None))
        outs = []
        for _ in range(2):
            with make_vit_wds_pipeline(ctx, paths, batch=8, image_size=32,
                                       sharding=sharding, seed=3,
                                       decode_workers=2) as pipe:
                outs.append(np.asarray(next(pipe)[0]))
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_rejects_inner_dim_sharding(self, ctx, mesh, wds_shards):
        """VERDICT.md weak #4: splitting H/W/C must fail fast at construction
        with a message naming the constraint, not opaquely inside
        make_array_from_single_device_arrays."""
        from strom.parallel.mesh import make_mesh
        from strom.pipelines import make_imagenet_resnet_pipeline

        paths, _ = wds_shards
        m2 = make_mesh({"dp": 4, "mp": 2}, devices=jax.devices()[:8])
        for bad in (P("dp", None, "mp", None), P("dp", "mp"),
                    P(None, None, None, "mp")):
            with pytest.raises(ValueError, match="batch-dim"):
                make_imagenet_resnet_pipeline(
                    ctx, paths, batch=8, image_size=32,
                    sharding=NamedSharding(m2, bad), decode_workers=2)

    def test_local_batch_rows_matches_indices_map(self, mesh):
        """Property: for every legal batch-only 4-D sharding, the row ranges
        the loader decodes equal what addressable_devices_indices_map says
        each device owns of the REAL global shape."""
        from strom.parallel.mesh import make_mesh
        from strom.pipelines.vision import (_local_batch_rows,
                                            _validate_batch_only)

        m2 = make_mesh({"dp": 4, "mp": 2}, devices=jax.devices()[:8])
        cases = [
            (mesh, P("dp", None, None, None), 16),
            (mesh, P("dp",), 8),                 # short spec, trailing None
            (mesh, P(None, None, None, None), 4),  # fully replicated
            (m2, P("dp", None, None, None), 8),  # mp axis replicates rows
            (m2, P(("dp", "mp"), None, None, None), 16),  # product sharding
        ]
        for m, spec, batch in cases:
            sharding = NamedSharding(m, spec)
            _validate_batch_only(sharding)
            got = _local_batch_rows(sharding, batch)
            shape = (batch, 32, 32, 3)
            expect = sharding.addressable_devices_indices_map(shape)
            assert set(got) == set(expect)
            for device, index in expect.items():
                sl = index[0] if index else slice(None)
                lo, hi, _ = sl.indices(batch)
                assert got[device] == (lo, hi), (spec, batch, device)

    def test_feeds_resnet_step(self, ctx, mesh, wds_shards):
        from strom.models.resnet import ResNetConfig, init_params, loss_fn
        from strom.pipelines import make_imagenet_resnet_pipeline

        paths, _ = wds_shards
        cfg = ResNetConfig.tiny()
        params, state = init_params(jax.random.PRNGKey(0), cfg)
        sharding = NamedSharding(mesh, P("dp", None, None, None))
        with make_imagenet_resnet_pipeline(
                ctx, paths, batch=8, image_size=32, sharding=sharding,
                decode_workers=2) as pipe:
            imgs, lbls = next(pipe)
            from strom.models.resnet import normalize_images

            loss, _ = jax.jit(loss_fn, static_argnames="cfg")(
                params, state, normalize_images(imgs), lbls, cfg)
        assert np.isfinite(float(loss))


# ----------------------------------------------------------- parquet scan
class TestParquetScan:
    @pytest.fixture(scope="class")
    def pq_shards(self, tmp_path_factory):
        import pyarrow as pa
        import pyarrow.parquet as pq

        rng = np.random.default_rng(31)
        td = tmp_path_factory.mktemp("pq")
        paths, frames = [], []
        for s in range(3):
            n = 4000
            vals = rng.normal(size=n)
            ids = np.arange(n, dtype=np.int64)
            table = pa.table({"id": pa.array(ids), "value": pa.array(vals)})
            p = str(td / f"part{s}.parquet")
            pq.write_table(table, p, row_group_size=1000)
            paths.append(p)
            frames.append(vals)
        return paths, np.concatenate(frames)

    def test_count_where_matches_numpy(self, ctx, pq_shards):
        from strom.pipelines import parquet_count_where

        paths, vals = pq_shards
        got = parquet_count_where(ctx, paths, "value", lambda v: v > 0.5)
        assert got == int((vals > 0.5).sum())

    @pytest.mark.parametrize("unit_batch", [2, 5, 100])
    def test_unit_batch_identical_results(self, ctx, pq_shards, unit_batch):
        """Batching row groups per dispatch must not change any aggregate
        (scan map_fns are row-decomposable); 100 > total units exercises the
        everything-in-one-dispatch edge."""
        from strom.pipelines import parquet_count_where

        paths, vals = pq_shards
        got = parquet_count_where(ctx, paths, "value", lambda v: v > 0.5,
                                  unit_batch=unit_batch)
        assert got == int((vals > 0.5).sum())

    def test_unit_batch_rejects_nonpositive(self, ctx, pq_shards):
        from strom.pipelines import parquet_count_where

        paths, _ = pq_shards
        with pytest.raises(ValueError, match="unit_batch"):
            parquet_count_where(ctx, paths, "value", lambda v: v > 0,
                                unit_batch=0)

    def test_zero_units_contributes_zero(self, ctx, pq_shards):
        """A process with no assigned units must produce a zero aggregate of
        the right structure, not raise (multi-host allgather safety)."""
        import jax.numpy as jnp

        from strom.pipelines import parquet_scan_aggregate

        paths, _ = pq_shards  # 3 shards × 4 row groups = 12 units

        def map_fn(cols):
            v = cols["value"]
            return {"sum": jnp.sum(v), "n": jnp.asarray(v.shape[0], jnp.int32)}

        # process 12 of 13: local_units = units[12::13] = []
        out = parquet_scan_aggregate(ctx, paths, ["value"], map_fn,
                                     process_index=12, process_count=13)
        assert out["sum"] == 0.0 and out["n"] == 0

    def test_round_robin_partition_sums_to_whole(self, ctx, pq_shards):
        """Simulated 3-process scan: per-process partials sum to the global."""
        import jax.numpy as jnp

        from strom.pipelines import parquet_scan_aggregate

        paths, vals = pq_shards
        parts = [parquet_scan_aggregate(
                     ctx, paths, ["value"],
                     lambda cols: jnp.sum(cols["value"]),
                     process_index=i, process_count=3) for i in range(3)]
        np.testing.assert_allclose(sum(parts), vals.sum(), rtol=1e-6)

    def test_aggregate_sum_matches(self, ctx, pq_shards):
        import jax.numpy as jnp

        from strom.pipelines import parquet_scan_aggregate

        paths, vals = pq_shards

        def map_fn_sum(cols):
            v = cols["value"]
            return {"sum": jnp.sum(v), "n": jnp.asarray(v.shape[0], jnp.int32)}

        out = parquet_scan_aggregate(ctx, paths, ["value"], map_fn_sum)
        assert out["n"] == len(vals)
        np.testing.assert_allclose(out["sum"], vals.sum(), rtol=1e-6)

    def test_wide_projection_scan(self, ctx, tmp_path):
        """Multi-column (wide) projection — the PG-Strom feature-vector
        shape the bench's WIDE arm uses: every selected column's chunks are
        engine-read and consumed by the aggregate, per-column sums exact."""
        pa = pytest.importorskip("pyarrow")
        import jax.numpy as jnp
        import pyarrow.parquet as pq

        from strom.pipelines import parquet_scan_aggregate

        rng = np.random.default_rng(23)
        cols = {f"f{i}": rng.standard_normal(4_000) for i in range(4)}
        path = str(tmp_path / "wide.parquet")
        pq.write_table(pa.table(cols), path, row_group_size=1_000)
        names = list(cols)

        def map_fn(d):
            return {c: jnp.sum(d[c]) for c in names}

        out = parquet_scan_aggregate(ctx, [path], names, map_fn,
                                     unit_batch=2)
        for c in names:
            # jax sums in float32 (x64 off); a 4k-element sum that cancels
            # toward zero needs an absolute floor alongside rtol
            np.testing.assert_allclose(out[c], cols[c].sum(),
                                       rtol=1e-4, atol=1e-3)

    def test_plain_encoded_scan_rides_direct_decoder(self, ctx, tmp_path):
        """Uncompressed PLAIN fixture (the bench's I/O-bound arm,
        VERDICT.md r4 next #1): the scan result is exact AND every selected
        byte went through the direct frombuffer decoder, none through
        pyarrow (the parquet_plain_bytes / parquet_decode_bytes counters
        prove which path ran)."""
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        from strom.pipelines import parquet_count_where
        from strom.utils.stats import global_stats

        rng = np.random.default_rng(29)
        vals = rng.standard_normal(12_000).astype(np.float32)
        path = str(tmp_path / "plain.parquet")
        pq.write_table(pa.table({"value": vals}), path,
                       row_group_size=3_000, compression="NONE",
                       use_dictionary=False)
        snap0 = global_stats.snapshot()
        got = parquet_count_where(ctx, [path], "value", lambda v: v > 0,
                                  unit_batch=2)
        snap1 = global_stats.snapshot()
        assert got == int((vals > 0).sum())
        # counter records chunk bytes: the values plus their page headers
        plain = snap1.get("parquet_plain_bytes", 0) \
            - snap0.get("parquet_plain_bytes", 0)
        assert vals.nbytes <= plain < vals.nbytes + 4096
        assert snap1.get("parquet_decode_bytes", 0) \
            == snap0.get("parquet_decode_bytes", 0)

    def test_bench_parquet_plain_disk_rate_smoke(self, tmp_path):
        """strom-bench parquet --compression none --disk-rate: the plain
        arm's artifact fields exist and cohere (vs_disk = best scan / best
        bare gather of the same extents; per-pass lists recorded)."""
        import argparse

        from strom.cli import bench_parquet

        out = bench_parquet(argparse.Namespace(
            file=None, size=0, block=4096, depth=8, iters=1,
            engine="python", tmpdir=str(tmp_path), json=True,
            rows=20_000, row_groups=4, prefetch=2, unit_batch=1,
            raid=0, raid_chunk=512 * 1024, columns=4,
            compression="none", dtype="float32", disk_rate=True,
            cpu_device=True))
        assert out["compression"] == "none"
        assert out["plain_decoded_bytes"] > 0
        assert out["pyarrow_decoded_bytes"] == 0
        assert len(out["selected_gbps_passes"]) == 2
        assert len(out["disk_gbps_passes"]) == 2
        assert out["disk_read_gbps"] == max(out["disk_gbps_passes"])
        assert out["vs_disk"] == pytest.approx(
            max(out["selected_gbps_passes"]) / out["disk_read_gbps"],
            rel=1e-2)

    def test_bench_parquet_raid_disk_rate_smoke(self, tmp_path):
        """--raid + --disk-rate: the bare-gather yardstick expands logical
        extents to member ops (the bench does the stripe math, the engine
        reads member ranges) — the striped scan gets a vs_disk too, with
        the scan's own hit count proving the data path."""
        import argparse

        from strom.cli import bench_parquet

        out = bench_parquet(argparse.Namespace(
            file=None, size=0, block=4096, depth=8, iters=1,
            engine="python", tmpdir=str(tmp_path), json=True,
            rows=20_000, row_groups=4, prefetch=2, unit_batch=2,
            raid=2, raid_chunk=64 * 1024, columns=4,
            compression="none", dtype="float32", disk_rate=True,
            cpu_device=True))
        assert out["raid_members"] == 2
        assert out["vs_disk"] is not None and out["vs_disk"] > 0
        assert len(out["disk_gbps_passes"]) == 2
        assert out["plain_decoded_bytes"] > 0  # striped + direct decode

    def test_decode_path_counters_in_prometheus(self, ctx, tmp_path):
        """The decode-path counters are observability surface (≙ the
        reference's /proc counters): after a scan they must appear in the
        Prometheus exposition, not only in the bench JSON."""
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        from strom.pipelines import parquet_count_where
        from strom.utils.stats import global_stats

        vals = np.random.default_rng(7).standard_normal(8_000) \
            .astype(np.float32)
        path = str(tmp_path / "prom.parquet")
        pq.write_table(pa.table({"value": vals}), path,
                       compression="NONE", use_dictionary=False)
        before = global_stats.snapshot().get("parquet_plain_bytes", 0)
        parquet_count_where(ctx, [path], "value", lambda v: v > 0)
        after = global_stats.snapshot().get("parquet_plain_bytes", 0)
        # THIS scan advanced the counter (key presence alone would pass
        # vacuously: global_stats is process-global and earlier tests have
        # already created the key)
        assert after > before
        assert f"strom_parquet_plain_bytes {after}" in \
            global_stats.prometheus()


class TestLlamaStriped:
    def test_striped_token_shards_golden(self, ctx, tmp_path):
        """Packed-token shards on a RAID0 striped set via path alias:
        sequential batches equal the logical token stream."""
        import os

        from jax.sharding import Mesh

        from strom.engine.raid0 import stripe_file
        from strom.pipelines import make_llama_pipeline

        seq, batch = 31, 8
        tokens = np.arange(8 * batch * (seq + 1), dtype=np.int32)
        plain = tmp_path / "tok.bin"
        tokens.tofile(plain)
        members = [str(tmp_path / f"tm{i}.bin") for i in range(4)]
        stripe_file(str(plain), members, 512)  # 8 chunks -> 2 per member
        virt = str(tmp_path / "tok_striped.bin")
        ctx.register_striped(virt, members, 512,
                             size=os.path.getsize(plain))

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
        sharding = NamedSharding(mesh, P("dp", None))
        with make_llama_pipeline(ctx, [virt], batch=batch, seq_len=seq,
                                 sharding=sharding, shuffle=False) as pipe:
            got = np.concatenate([np.asarray(next(pipe)).ravel()
                                  for _ in range(4)])
        np.testing.assert_array_equal(got, tokens[:got.size])


class TestPredecodedPipeline:
    @pytest.fixture(scope="class")
    def pdec_shard(self, tmp_path_factory, ctx):
        """A WDS tar decoded once into a packed uint8 shard."""
        import cv2

        from strom.formats.predecoded import predecode_wds
        from tests.test_formats import make_wds_shard

        rng = np.random.default_rng(31)
        td = tmp_path_factory.mktemp("pdec")
        samples = []
        for i in range(20):
            img = rng.integers(0, 256, (48, 64, 3), dtype=np.uint8)
            ok, buf = cv2.imencode(".jpg", img)
            assert ok
            samples.append((f"s{i:04d}", {"jpg": buf.tobytes(),
                                          "cls": str(i % 7).encode()}))
        tar = str(td / "src.tar")
        make_wds_shard(tar, samples)
        out = predecode_wds(ctx, [tar], str(td / "imgs.pdec"), image_size=32,
                            decode_workers=2)
        return out

    def test_format_roundtrip(self, ctx, pdec_shard):
        """Records are image_size^2*3 bytes, labels ride the sidecar, and
        the extents gather returns exactly the packed record bytes."""
        from strom.formats.predecoded import PredecodedShardSet

        ss = PredecodedShardSet((pdec_shard,), 32)
        assert ss.num_records == 20
        assert ss.record_bytes == 32 * 32 * 3
        np.testing.assert_array_equal(ss.labels(range(20)),
                                      [i % 7 for i in range(20)])
        raw = np.fromfile(pdec_shard, dtype=np.uint8)
        got = np.asarray(memoryview(ctx.pread(ss.extents([3, 4, 11]))))
        rb = ss.record_bytes
        np.testing.assert_array_equal(
            got, np.concatenate([raw[3 * rb: 5 * rb], raw[11 * rb: 12 * rb]]))

    def test_wrong_image_size_rejected(self, pdec_shard):
        from strom.formats.predecoded import PredecodedShardSet

        with pytest.raises(ValueError, match="image_size"):
            PredecodedShardSet((pdec_shard,), 64)

    def test_pipeline_batches_and_determinism(self, ctx, mesh, pdec_shard):
        """Decode-free loader delivers [B,S,S,3] uint8 sharded batches whose
        bytes equal the packed records, labels aligned, deterministic in
        seed."""
        from strom.pipelines import make_predecoded_vision_pipeline

        sharding = NamedSharding(mesh, P("dp", None, None, None))
        raw = np.fromfile(pdec_shard, dtype=np.uint8).reshape(20, 32, 32, 3)
        with make_predecoded_vision_pipeline(
                ctx, [pdec_shard], batch=8, image_size=32, sharding=sharding,
                shuffle=False) as pipe:
            imgs, lbls = next(pipe)
        assert imgs.shape == (8, 32, 32, 3) and imgs.dtype == np.uint8
        assert imgs.sharding == sharding
        np.testing.assert_array_equal(np.asarray(imgs), raw[:8])
        np.testing.assert_array_equal(np.asarray(lbls),
                                      [i % 7 for i in range(8)])
        # shuffled: two pipelines with the same seed agree
        outs = []
        for _ in range(2):
            with make_predecoded_vision_pipeline(
                    ctx, [pdec_shard], batch=8, image_size=32,
                    sharding=sharding, seed=5) as pipe:
                outs.append(np.asarray(next(pipe)[0]))
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_stale_labels_sidecar_rejected(self, ctx, pdec_shard, tmp_path):
        """A predecode interrupted between the records rename and the
        sidecar renames leaves new records with OLD sidecars (sidecars land
        strictly after records — ADVICE.md r3 #1): the loader must refuse
        the count mismatch, not silently mislabel every sample."""
        import shutil

        from strom.formats.predecoded import (LABELS_SUFFIX, META_SUFFIX,
                                              PredecodedShardSet)

        clone = str(tmp_path / "stale.pdec")
        shutil.copyfile(pdec_shard, clone)
        shutil.copyfile(pdec_shard + META_SUFFIX, clone + META_SUFFIX)
        np.save(clone + LABELS_SUFFIX + ".tmp.npy",
                np.zeros(7, np.int32))  # wrong count = stale generation
        os.replace(clone + LABELS_SUFFIX + ".tmp.npy", clone + LABELS_SUFFIX)
        with pytest.raises(ValueError, match="stale"):
            PredecodedShardSet((clone,), 32)

    def test_predecode_leaves_no_tmp_files(self, pdec_shard):
        """The atomic-staging protocol cleans up: no .tmp leftovers beside
        the shard after a successful predecode."""
        d = os.path.dirname(pdec_shard)
        leftovers = [f for f in os.listdir(d) if ".tmp" in f]
        assert leftovers == []

    def test_rejects_inner_dim_sharding(self, ctx, pdec_shard):
        from strom.parallel.mesh import make_mesh
        from strom.pipelines import make_predecoded_vision_pipeline
        import jax

        mesh2 = make_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
        bad = NamedSharding(mesh2, P("dp", "mp", None, None))
        with pytest.raises(ValueError, match="batch-dim"):
            make_predecoded_vision_pipeline(ctx, [pdec_shard], batch=8,
                                            image_size=32, sharding=bad)

    def test_checkpoint_resume_replays_nothing(self, ctx, mesh, pdec_shard):
        """Mid-epoch resume of the decode-free loader: batches after the
        cursor match an uninterrupted run exactly (images AND labels)."""
        from strom.pipelines import make_predecoded_vision_pipeline

        sharding = NamedSharding(mesh, P("dp", None, None, None))

        def make(resume=None):
            return make_predecoded_vision_pipeline(
                ctx, [pdec_shard], batch=8, image_size=32, sharding=sharding,
                seed=9, resume_from=resume)

        with make() as pipe:
            golden = [next(pipe) for _ in range(4)]
            golden = [(np.asarray(i), np.asarray(l)) for i, l in golden]
        with make() as pipe:
            next(pipe)
            next(pipe)
            state = pipe.state()
            resumed = make(resume=state)
        with resumed as pipe:
            for want_i, want_l in golden[2:]:
                got_i, got_l = next(pipe)
                np.testing.assert_array_equal(np.asarray(got_i), want_i)
                np.testing.assert_array_equal(np.asarray(got_l), want_l)


class TestScanReduction:
    def test_reduce_modes_agree(self, ctx, tmp_path):
        """Both reductions — the XLA-collective scan-mesh sum and the
        allgather fallback — give the same count on the 8-device CPU mesh
        (single process: the collective runs as a local-mesh reduction)."""
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        from strom.pipelines import parquet_count_where

        rng = np.random.default_rng(77)
        vals = rng.standard_normal(6000)
        p = str(tmp_path / "r.parquet")
        pq.write_table(pa.table({"value": pa.array(vals)}), p,
                       row_group_size=1000)
        truth = int((vals > 0).sum())
        for reduce in ("collective", "allgather"):
            got = parquet_count_where(ctx, [p], "value", lambda v: v > 0,
                                      reduce=reduce)
            assert got == truth, reduce

    def test_reduce_mode_validated(self, ctx, tmp_path):
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        from strom.pipelines import parquet_count_where

        p = str(tmp_path / "v.parquet")
        pq.write_table(pa.table({"value": pa.array(np.ones(10))}), p)
        with pytest.raises(ValueError, match="reduce"):
            parquet_count_where(ctx, [p], "value", lambda v: v > 0,
                                reduce="psum")


class TestPredecodedStriped:
    def test_striped_predecoded_pipeline(self, ctx, mesh, tmp_path, rng):
        """The decode-once shard striped RAID0-style and read through a path
        alias: batches byte-equal the plain shard's records, labels via the
        alias-named sidecar (config #3's decode-free arm)."""
        import cv2

        from strom.formats.predecoded import (predecode_wds,
                                              stage_striped_predecoded)
        from strom.pipelines import make_predecoded_vision_pipeline
        from tests.test_formats import make_wds_shard

        samples = []
        for i in range(16):
            img = rng.integers(0, 256, (40, 40, 3), dtype=np.uint8)
            ok, buf = cv2.imencode(".jpg", img)
            assert ok
            samples.append((f"s{i:04d}", {"jpg": buf.tobytes(),
                                          "cls": str(i % 5).encode()}))
        tar = str(tmp_path / "src.tar")
        make_wds_shard(tar, samples)
        pdec = predecode_wds(ctx, [tar], str(tmp_path / "imgs.pdec"),
                             image_size=32, decode_workers=2)
        members = [str(tmp_path / f"pm{i}.bin") for i in range(2)]
        virt = stage_striped_predecoded(ctx, pdec, members, 64 * 1024)

        raw = np.fromfile(pdec, dtype=np.uint8).reshape(16, 32, 32, 3)
        sharding = NamedSharding(mesh, P("dp", None, None, None))
        with make_predecoded_vision_pipeline(
                ctx, [virt], batch=8, image_size=32, sharding=sharding,
                shuffle=False) as pipe:
            imgs, lbls = next(pipe)
        np.testing.assert_array_equal(np.asarray(imgs), raw[:8])
        np.testing.assert_array_equal(np.asarray(lbls),
                                      [i % 5 for i in range(8)])
