"""Sanitizer jobs: the C++ engine under TSAN/ASAN with concurrent submitters
(SURVEY.md §5 'Race detection/sanitizers' row).

The sanitized .so is loaded into a stock (non-sanitized) python, so the
runtime must be LD_PRELOADed into a subprocess; sanitizer reports land on
stderr and flip the exit code via TSAN_OPTIONS/ASAN_OPTIONS."""

import os
import subprocess
import sys

import pytest


def _runtime(name: str) -> str | None:
    try:
        out = subprocess.run(["g++", f"-print-file-name={name}"],
                             capture_output=True, text=True).stdout.strip()
    except OSError:
        return None
    return out if out and os.path.isabs(out) and os.path.exists(out) else None


def _run_stress(variant: str, preload: str, extra_env: dict,
                *extra_args: str) -> subprocess.CompletedProcess:
    env = os.environ.copy()
    env["LD_PRELOAD"] = preload
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-m", "strom.engine.stress", "--variant", variant,
         "--seconds", "2", *extra_args],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
def test_tsan_stress_clean():
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    rt = _runtime("libtsan.so")
    if rt is None:
        pytest.skip("libtsan runtime not found")
    proc = _run_stress("tsan", rt, {
        # history_size: keep memory modest; exitcode flips on any report
        "TSAN_OPTIONS": "exitcode=66 report_bugs=1 history_size=2",
    })
    assert "ThreadSanitizer" not in proc.stderr, proc.stderr[-4000:]
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-4000:])
    assert "stress ok" in proc.stdout


@pytest.mark.slow
def test_asan_stress_clean():
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    rt = _runtime("libasan.so")
    if rt is None:
        pytest.skip("libasan runtime not found")
    proc = _run_stress("asan", rt, {
        # python itself "leaks" interned objects: leak detection off, the
        # memory-error detectors (UAF/OOB) stay on
        "ASAN_OPTIONS": "detect_leaks=0 exitcode=67",
    })
    assert "AddressSanitizer" not in proc.stderr, proc.stderr[-4000:]
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-4000:])
    assert "stress ok" in proc.stdout


@pytest.mark.slow
def test_tsan_stress_sqpoll_clean():
    """The SQPOLL submit path (seq_cst fence + NEED_WAKEUP check racing the
    kernel poller, zero-syscall publishes racing reapers) under TSAN."""
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    rt = _runtime("libtsan.so")
    if rt is None:
        pytest.skip("libtsan runtime not found")
    # probe first: the kernel may legitimately refuse SQPOLL (unprivileged
    # pre-5.13, rlimit-constrained containers) and the engine's contract is
    # silent fallback — a vacuous fallback run here should skip, not fail
    from strom.config import StromConfig
    from strom.engine import make_engine

    probe = make_engine(StromConfig(sqpoll=True, queue_depth=8, num_buffers=8))
    try:
        if not probe.stats().get("sqpoll"):
            pytest.skip("kernel refuses IORING_SETUP_SQPOLL here")
    finally:
        probe.close()
    proc = _run_stress("tsan", rt, {
        "TSAN_OPTIONS": "exitcode=66 report_bugs=1 history_size=2",
    }, "--sqpoll")
    assert "ThreadSanitizer" not in proc.stderr, proc.stderr[-4000:]
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-4000:])
    assert "stress ok" in proc.stdout
    # the probe said SQPOLL engages on this kernel, so a fallback in the
    # stress subprocess means the flag plumbing regressed — fail loudly
    assert "sqpoll=True" in proc.stdout, proc.stdout


@pytest.mark.slow
def test_tsan_multiring_stress_clean():
    """Concurrent gathers across a 2-ring engine with NO delivery-layer
    lock (concurrent_gathers): the per-ring locking, lazy cross-ring file
    registration, and every-ring dest registration under TSAN."""
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    rt = _runtime("libtsan.so")
    if rt is None:
        pytest.skip("libtsan runtime not found")
    proc = _run_stress("tsan", rt, {
        "TSAN_OPTIONS": "exitcode=66 report_bugs=1 history_size=2",
    }, "--rings", "2")
    assert "ThreadSanitizer" not in proc.stderr, proc.stderr[-4000:]
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-4000:])
    assert "stress ok" in proc.stdout
