"""Sanitizer jobs: the C++ engine under TSAN/ASAN with concurrent submitters
(SURVEY.md §5 'Race detection/sanitizers' row).

The sanitized .so is loaded into a stock (non-sanitized) python, so the
runtime must be LD_PRELOADed into a subprocess; sanitizer reports land on
stderr and flip the exit code via TSAN_OPTIONS/ASAN_OPTIONS."""

import os
import subprocess
import sys

import pytest


def _runtime(name: str) -> str | None:
    try:
        out = subprocess.run(["g++", f"-print-file-name={name}"],
                             capture_output=True, text=True).stdout.strip()
    except OSError:
        return None
    return out if out and os.path.isabs(out) and os.path.exists(out) else None


def _run_stress(variant: str, preload: str, extra_env: dict) -> subprocess.CompletedProcess:
    env = os.environ.copy()
    env["LD_PRELOAD"] = preload
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-m", "strom.engine.stress", "--variant", variant,
         "--seconds", "2"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
def test_tsan_stress_clean():
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    rt = _runtime("libtsan.so")
    if rt is None:
        pytest.skip("libtsan runtime not found")
    proc = _run_stress("tsan", rt, {
        # history_size: keep memory modest; exitcode flips on any report
        "TSAN_OPTIONS": "exitcode=66 report_bugs=1 history_size=2",
    })
    assert "ThreadSanitizer" not in proc.stderr, proc.stderr[-4000:]
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-4000:])
    assert "stress ok" in proc.stdout


@pytest.mark.slow
def test_asan_stress_clean():
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    rt = _runtime("libasan.so")
    if rt is None:
        pytest.skip("libasan runtime not found")
    proc = _run_stress("asan", rt, {
        # python itself "leaks" interned objects: leak detection off, the
        # memory-error detectors (UAF/OOB) stay on
        "ASAN_OPTIONS": "detect_leaks=0 exitcode=67",
    })
    assert "AddressSanitizer" not in proc.stderr, proc.stderr[-4000:]
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-4000:])
    assert "stress ok" in proc.stdout
