"""Failure handling in the delivery layer: transparent chunk retry, loud
failure past the retry budget (SURVEY.md §5 'Failure detection' row)."""

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.engine.base import EngineError
from strom.utils.stats import global_stats


class TestChunkRetry:
    # residency_hybrid=False everywhere here: these tests exercise the MEDIA
    # retry path at block_size chunking, and the hybrid would serve the
    # just-written (warm) fixture as far fewer, larger buffered ops —
    # shifting the fault_every parity the assertions rely on. The config
    # knob is deterministic where cache eviction is only advisory.

    def test_faults_absorbed_by_retry(self, engine_name, data_file):
        """fault_every=5 at qd=4: plenty of ops fault, every one retries
        successfully, delivered bytes stay golden."""
        path, golden = data_file
        cfg = StromConfig(engine=engine_name, queue_depth=4, num_buffers=8,
                          fault_every=5, io_retries=1,
                          residency_hybrid=False)
        before = global_stats.counter("chunk_retries").value
        ctx = StromContext(cfg)
        try:
            got = ctx.pread(path, 0, 2 * 1024 * 1024)
        finally:
            ctx.close()
        np.testing.assert_array_equal(got, golden[: 2 * 1024 * 1024])
        assert global_stats.counter("chunk_retries").value > before

    def test_retry_budget_zero_fails_loudly(self, engine_name, data_file):
        path, _ = data_file
        cfg = StromConfig(engine=engine_name, queue_depth=4, num_buffers=8,
                          fault_every=2, io_retries=0,
                          residency_hybrid=False)
        ctx = StromContext(cfg)
        try:
            with pytest.raises(EngineError, match="after 1 attempts"):
                ctx.pread(path, 0, 2 * 1024 * 1024)
        finally:
            ctx.close()

    def test_persistent_fault_exhausts_retries(self, engine_name, data_file):
        """fault_every=1 faults every op including retries: must fail, not
        loop forever."""
        path, _ = data_file
        cfg = StromConfig(engine=engine_name, queue_depth=4, num_buffers=8,
                          fault_every=1, io_retries=2,
                          residency_hybrid=False)
        ctx = StromContext(cfg)
        try:
            with pytest.raises(EngineError, match="after 3 attempts"):
                ctx.pread(path, 0, 512 * 1024)
        finally:
            ctx.close()

    def test_engine_usable_after_failed_transfer(self, engine_name, data_file):
        """A failed transfer must not poison the shared engine for later ones."""
        path, golden = data_file
        cfg = StromConfig(engine=engine_name, queue_depth=4, num_buffers=8,
                          fault_every=2, io_retries=0,
                          residency_hybrid=False)
        ctx = StromContext(cfg)
        try:
            with pytest.raises(EngineError):
                ctx.pread(path, 0, 1024 * 1024)
            # stop injecting: the next transfer must succeed cleanly
            object.__setattr__(ctx.config, "fault_every", 0)
            if hasattr(ctx.engine, "set_fault_every"):
                ctx.engine.set_fault_every(0)
            else:
                object.__setattr__(ctx.engine.config, "fault_every", 0)
            got = ctx.pread(path, 4096, 256 * 1024)
            np.testing.assert_array_equal(got, golden[4096: 4096 + 256 * 1024])
        finally:
            ctx.close()
