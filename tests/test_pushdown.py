"""ISSUE 19 near-data pushdown: the predicate IR's refutation rules,
plan-time pushdown vs post-hoc filtering bit-identity (including
missing-stats conservatism), OpGraph fused-vs-unfused parity, compressed
spill/peer tiers (off-path = pre-PR wire/file layout, mixed fleets
downgrade per peer), and the new autotuner surfaces."""

import os
import socket
import threading

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.ops.pushdown import ColStats, col


def _cfg(**kw):
    base = dict(engine="python", queue_depth=8, num_buffers=8,
                hot_cache_bytes=64 * 1024 * 1024, hot_cache_admit="always")
    base.update(kw)
    return StromConfig(**base)


# ---------------------------------------------------------------- predicate
class TestPredicate:
    def test_cmp_refutation(self):
        st = {"x": ColStats(10, 20, 0)}
        assert (col("x") < 10).refutes(st)
        assert not (col("x") < 11).refutes(st)
        assert (col("x") <= 9).refutes(st)
        assert (col("x") > 20).refutes(st)
        assert not (col("x") >= 20).refutes(st)
        assert (col("x") == 9).refutes(st)
        assert not (col("x") == 15).refutes(st)

    def test_missing_stats_conservative(self):
        # no stats / partial stats / incomparable stats: never refute
        assert not (col("x") < 0).refutes({})
        assert not (col("x") < 0).refutes({"x": ColStats(None, None, 0)})
        assert not (col("x") < 0).refutes({"x": ColStats(b"a", b"z", 0)})

    def test_ne_needs_constant_group_without_nulls(self):
        assert (col("x") != 5).refutes({"x": ColStats(5, 5, 0)})
        # unknown null count: a null would decode to NaN and NaN != 5
        assert not (col("x") != 5).refutes({"x": ColStats(5, 5, None)})
        assert not (col("x") != 5).refutes({"x": ColStats(5, 6, 0)})

    def test_and_or_composition(self):
        st = {"x": ColStats(10, 20, 0), "y": ColStats(0, 1, 0)}
        # one refuted conjunct refutes the conjunction
        assert ((col("x") < 5) & (col("y") >= 0)).refutes(st)
        # one live disjunct saves the disjunction
        assert not ((col("x") < 5) | (col("y") >= 0)).refutes(st)
        assert ((col("x") < 5) | (col("y") > 1)).refutes(st)
        p = (col("x") < 5) | (col("y") > 1)
        assert p.columns() == frozenset({"x", "y"})

    def test_mask_matches_numpy(self):
        cols_ = {"x": np.arange(10), "y": np.arange(10) % 3}
        m = ((col("x") >= 4) & (col("y") == 0)).mask(cols_)
        np.testing.assert_array_equal(
            m, (np.arange(10) >= 4) & (np.arange(10) % 3 == 0))


# ------------------------------------------------------- plan-time pushdown
class TestParquetPushdown:
    ROWS, GROUPS = 4000, 8

    def _write(self, tmp_path, name, **kw):
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        rng = np.random.default_rng(3)
        path = str(tmp_path / name)
        # monotone seq: disjoint per-group min/max, so a cutoff predicate
        # refutes a controlled set of groups
        pq.write_table(pa.table({
            "seq": np.arange(self.ROWS, dtype=np.int64),
            "value": rng.integers(0, 1000, self.ROWS, dtype=np.int64),
        }), path, row_group_size=self.ROWS // self.GROUPS, **kw)
        return path

    def _scan_pair(self, ctx, path, cutoff):
        """(pushed, post-hoc) integer aggregates — int sums are
        order-independent, so equality here is bit-identity."""
        import jax.numpy as jnp

        from strom.pipelines.parquet_scan import parquet_scan_aggregate

        def m_push(d):
            return {"hits": jnp.sum((d["value"] > 500).astype(jnp.int32)),
                    "ssum": jnp.sum(d["seq"].astype(jnp.int32))}

        def m_post(d):
            keep = d["seq"] < cutoff
            return {"hits": jnp.sum(((d["value"] > 500) & keep)
                                    .astype(jnp.int32)),
                    "ssum": jnp.sum(jnp.where(keep, d["seq"], 0)
                                    .astype(jnp.int32))}

        pushed = parquet_scan_aggregate(ctx, [path], ["value", "seq"],
                                        m_push, predicate=col("seq") < cutoff)
        post = parquet_scan_aggregate(ctx, [path], ["value", "seq"], m_post)
        return ({k: int(v) for k, v in pushed.items()},
                {k: int(v) for k, v in post.items()})

    def test_pushdown_bit_identical_and_skips(self, tmp_path):
        from strom.ops.pushdown import PUSHDOWN_FIELDS
        from strom.utils.stats import global_stats

        path = self._write(tmp_path, "push.parquet")
        # 750 straddles group 1 (rows 500..999): exercises the row-mask
        # half as well as whole-group refutation of groups 2..7
        cutoff = 750
        ctx = StromContext(_cfg())
        try:
            snap0 = global_stats.snapshot()
            pushed, post = self._scan_pair(ctx, path, cutoff)
            snap1 = global_stats.snapshot()
        finally:
            ctx.close()
        assert pushed == post
        d = {k: snap1.get(k, 0) - snap0.get(k, 0) for k in PUSHDOWN_FIELDS}
        assert d["parquet_pushdown_groups_total"] == self.GROUPS
        assert d["parquet_pushdown_groups_skipped"] == 6
        assert d["parquet_pushdown_skipped_bytes"] > 0
        assert d["parquet_pushdown_submitted_bytes"] < \
            d["parquet_pushdown_skipped_bytes"] \
            + d["parquet_pushdown_submitted_bytes"]
        # group 1 survives the stats pass but rows 750..999 mask out
        assert d["parquet_pushdown_rows_masked"] == 250

    def test_missing_stats_groups_conservatively_pass(self, tmp_path):
        """A file written without column statistics refutes nothing at
        plan time — every group submits — and the row-mask half alone
        still reproduces the post-hoc result bit-identically."""
        from strom.ops.pushdown import PUSHDOWN_FIELDS
        from strom.utils.stats import global_stats

        path = self._write(tmp_path, "nostats.parquet",
                           write_statistics=False)
        ctx = StromContext(_cfg())
        try:
            snap0 = global_stats.snapshot()
            pushed, post = self._scan_pair(ctx, path, 750)
            snap1 = global_stats.snapshot()
        finally:
            ctx.close()
        assert pushed == post
        d = {k: snap1.get(k, 0) - snap0.get(k, 0) for k in PUSHDOWN_FIELDS}
        assert d["parquet_pushdown_groups_total"] == self.GROUPS
        assert d["parquet_pushdown_groups_skipped"] == 0
        assert d["parquet_pushdown_skipped_bytes"] == 0

    def test_all_groups_refuted_yields_zero(self, tmp_path):
        path = self._write(tmp_path, "allout.parquet")
        ctx = StromContext(_cfg())
        try:
            pushed, post = self._scan_pair(ctx, path, -1)
        finally:
            ctx.close()
        assert pushed == post == {"hits": 0, "ssum": 0}


# ----------------------------------------------------------- OpGraph parity
class TestOpGraphParity:
    def test_fused_matches_unfused_and_streamed(self, tmp_path):
        """The fused per-sample kernel on the decode pool must be
        bit-identical to per-op application, with and without intra-batch
        streaming, and the per-op engagement counters must move."""
        cv2 = pytest.importorskip("cv2")
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from strom.ops import OpGraph
        from strom.parallel.mesh import make_mesh
        from strom.pipelines.vision import make_wds_vision_pipeline
        from strom.utils.stats import global_stats
        from tests.test_formats import make_wds_shard

        rng = np.random.default_rng(5)
        samples = []
        for i in range(24):
            img = rng.integers(0, 256, (48 + (i % 5), 56, 3), dtype=np.uint8)
            ok, buf = cv2.imencode(".jpg", img)
            assert ok
            samples.append((f"s{i:04d}", {"jpg": buf.tobytes(),
                                          "cls": str(i % 10).encode()}))
        path = str(tmp_path / "og.tar")
        make_wds_shard(path, samples)
        mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
        sharding = NamedSharding(mesh, P("dp", None, None, None))

        def graph():
            return (OpGraph()
                    .filter(lambda x: x[0, 0, 0] < 250)
                    .project(slice(0, 24), slice(0, 24))
                    .normalize([127.5] * 3, [63.0] * 3)
                    .cast(np.float32))

        def run(fuse, stream):
            ctx = StromContext(_cfg(num_buffers=16))
            out = []
            try:
                with make_wds_vision_pipeline(
                        ctx, [path], batch=8, image_size=32,
                        sharding=sharding, seed=11, decode_workers=2,
                        stream_intra_batch=stream, opgraph=graph(),
                        opgraph_fuse=fuse) as pipe:
                    for _ in range(pipe.sampler.batches_per_epoch * 2):
                        imgs, lbls = next(pipe)
                        out.append((np.asarray(imgs), np.asarray(lbls)))
            finally:
                ctx.close()
            return out

        fused = run(True, True)
        unfused = run(False, False)
        fused_nostream = run(True, False)
        assert fused[0][0].shape == (8, 24, 24, 3)
        assert fused[0][0].dtype == np.float32
        for (ia, la), (ib, lb), (ic, _lc) in zip(fused, unfused,
                                                 fused_nostream):
            np.testing.assert_array_equal(ia, ib)
            np.testing.assert_array_equal(ia, ic)
            np.testing.assert_array_equal(la, lb)
        snap = global_stats.snapshot()
        for k in ("ops_graph_samples", "ops_graph_runs",
                  "ops_normalize_samples"):
            assert snap.get(k, 0) > 0, k


# ------------------------------------------------------- compressed spill
class TestSpillCompression:
    def test_compressed_round_trip(self, tmp_path):
        from strom.delivery.spill import SpillTier

        sp = SpillTier(str(tmp_path / "spill.bin"), 16 << 20, compress=True)
        try:
            data = np.tile(np.arange(64, dtype=np.uint8), 4096)  # 256 KiB
            n = data.nbytes
            assert sp.offer("k1", 0, n, data) == n
            hits, misses = sp.lookup("k1", 0, n)
            assert not misses and len(hits) == 1
            s, t, ent = hits[0]
            assert ent.codec is not None and ent.stored < n
            # compressed entries cannot serve via sendfile/file ranges
            assert sp.file_range(ent, s, t) is None
            dest = np.empty(n, np.uint8)
            sp.read_into(ent, 0, n, dest)
            np.testing.assert_array_equal(dest, data)
            # partial-range read decompresses and slices
            part = np.empty(1000, np.uint8)
            sp.read_into(ent, 500, 1500, part)
            np.testing.assert_array_equal(part, data[500:1500])
            sp.unpin([ent])
            st = sp.stats()
            assert st["spill_comp_bytes_in"] == n
            assert 0 < st["spill_comp_bytes_out"] < n
            assert st["spill_comp_ratio"] > 1.0
            assert st["spill_decomp_bytes"] == n + 1000
        finally:
            sp.close()

    def test_incompressible_rides_raw(self, tmp_path):
        from strom.delivery.spill import SpillTier

        sp = SpillTier(str(tmp_path / "spill.bin"), 16 << 20, compress=True)
        try:
            rnd = np.random.default_rng(0).integers(
                0, 255, 64 << 10, dtype=np.uint8)
            sp.offer("k", 0, rnd.nbytes, rnd)
            hits, misses = sp.lookup("k", 0, rnd.nbytes)
            assert not misses
            _, _, ent = hits[0]
            # the codec didn't pay: stored raw, file ranges still served
            assert ent.codec is None
            assert sp.file_range(ent, 0, rnd.nbytes) is not None
            dest = np.empty(rnd.nbytes, np.uint8)
            sp.read_into(ent, 0, rnd.nbytes, dest)
            np.testing.assert_array_equal(dest, rnd)
            sp.unpin([ent])
        finally:
            sp.close()

    def test_compress_off_is_pre_pr_path(self, tmp_path):
        from strom.delivery.spill import SpillTier

        sp = SpillTier(str(tmp_path / "spill.bin"), 16 << 20)
        try:
            data = np.tile(np.arange(64, dtype=np.uint8), 4096)
            n = data.nbytes
            sp.offer("k", 0, n, data)
            hits, _ = sp.lookup("k", 0, n)
            _, _, ent = hits[0]
            assert ent.codec is None and ent.stored == n
            assert sp.file_range(ent, 0, n) is not None
            sp.unpin([ent])
        finally:
            sp.close()


# --------------------------------------------------------- compressed peers
def _peer_pair(tmp_path, payload, server_cfg, client_cfg):
    p = os.path.join(str(tmp_path), "data.bin")
    payload.tofile(p)
    a = StromContext(server_cfg)
    b = StromContext(client_cfg)
    addr = a.serve_peers()
    a.pread(p, 0, payload.nbytes)  # warm the owner
    b.attach_peers({0: addr}, owner_fn=lambda path: 0)
    return a, b, p


class TestPeerCompression:
    PAYLOAD = np.tile(np.arange(251, dtype=np.uint8), 1024)

    def test_comp_both_sides(self, tmp_path):
        a, b, p = _peer_pair(tmp_path, self.PAYLOAD,
                             _cfg(peer_compress=True),
                             _cfg(peer_compress=True))
        try:
            got = b.pread(p, 0, 4096)
            assert bytes(got) == self.PAYLOAD[:4096].tobytes()
            st = a._peer_server.stats()
            assert st["peer_comp_bytes_in"] == 4096
            assert 0 < st["peer_comp_bytes_out"] < 4096
            assert st["peer_comp_ratio"] > 1.0
            info = next(iter(b.peer_tier.peers_info().values()))
            assert info["comp_ok"] is True
        finally:
            a.close()
            b.close()

    def test_comp_client_raw_server(self, tmp_path):
        """Server without compression answers a comp request with a raw
        hit — the client keeps asking (the op WAS understood)."""
        a, b, p = _peer_pair(tmp_path, self.PAYLOAD, _cfg(),
                             _cfg(peer_compress=True))
        try:
            got = b.pread(p, 0, 4096)
            assert bytes(got) == self.PAYLOAD[:4096].tobytes()
            assert a._peer_server.stats()["peer_comp_bytes_in"] == 0
            info = next(iter(b.peer_tier.peers_info().values()))
            assert info["comp_ok"] is True
        finally:
            a.close()
            b.close()

    def test_raw_client_comp_server(self, tmp_path):
        """Nothing compresses without the ask on the wire — the off-path
        client sees the pre-PR protocol byte for byte."""
        a, b, p = _peer_pair(tmp_path, self.PAYLOAD,
                             _cfg(peer_compress=True), _cfg())
        try:
            got = b.pread(p, 0, 4096)
            assert bytes(got) == self.PAYLOAD[:4096].tobytes()
            assert a._peer_server.stats()["peer_comp_bytes_in"] == 0
        finally:
            a.close()
            b.close()

    def test_doesnt_pay_fallback_counted(self, tmp_path):
        rnd = np.random.default_rng(1).integers(
            0, 255, 256 * 1024, dtype=np.uint8)
        a, b, p = _peer_pair(tmp_path, rnd, _cfg(peer_compress=True),
                             _cfg(peer_compress=True))
        try:
            got = b.pread(p, 0, 4096)
            assert bytes(got) == rnd[:4096].tobytes()
            assert a._peer_server.stats()["peer_comp_fallbacks"] >= 1
        finally:
            a.close()
            b.close()

    def test_old_peer_downgrade_ladder(self, tmp_path):
        """A pre-compression peer that kills the connection on any op it
        doesn't know: the client must latch comp_ok=False first, then
        trace_ok=False, and finally be served over plain OP_GET."""
        from strom.dist.peers import (OP_GET, ST_HIT, _REQ_HEAD, recv_frame,
                                      send_frame)

        blob = self.PAYLOAD.tobytes()
        p = os.path.join(str(tmp_path), "data.bin")
        self.PAYLOAD.tofile(p)
        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(4)
        stop = threading.Event()

        def old_peer():
            while not stop.is_set():
                try:
                    conn, _ = lsock.accept()
                except OSError:
                    return
                try:
                    while True:
                        fr = recv_frame(conn)
                        op, _plen = _REQ_HEAD.unpack_from(fr, 0)
                        if op != OP_GET:
                            conn.close()  # old wire: unknown op = dead conn
                            break
                        send_frame(conn, (bytes([ST_HIT]), blob[:4096]))
                except Exception:
                    pass
                finally:
                    try:
                        conn.close()
                    except OSError:
                        pass

        t = threading.Thread(target=old_peer, daemon=True)
        t.start()
        b = StromContext(_cfg(peer_compress=True))
        try:
            b.attach_peers({0: f"127.0.0.1:{lsock.getsockname()[1]}"},
                           owner_fn=lambda path: 0)
            tier = b.peer_tier
            # 1st fetch: comp+traced op, conn dropped, comp_ok latches
            assert tier.fetch(p, 0, 4096) is None
            info = next(iter(tier.peers_info().values()))
            assert info["comp_ok"] is False
            # 2nd: traced-uncompressed, still dropped, trace_ok latches
            assert tier.fetch(p, 0, 4096) is None
            info = next(iter(tier.peers_info().values()))
            assert info["trace_ok"] is False
            # 3rd: plain OP_GET — served
            got = tier.fetch(p, 0, 4096)
            assert got is not None and bytes(got) == blob[:4096]
        finally:
            stop.set()
            lsock.close()
            b.close()


# ----------------------------------------------------------- tuner surfaces
class _Pool:
    run_target_us = 4000.0


class _RA:
    def __init__(self, n):
        self.window_batches = n


class TestTunables:
    def test_registered_surfaces_become_knobs(self):
        from strom.tune.knobs import standard_knobs

        ctx = StromContext(_cfg())
        try:
            pool, ra = _Pool(), _RA(4)
            ctx.register_tunable("decode_pool", pool)
            ctx.register_tunable("readahead", ra)
            knobs = {k.name: k for k in standard_knobs(ctx)}
            assert "decode_run_target_us" in knobs
            assert "readahead_window_batches" in knobs
            knobs["decode_run_target_us"].set(9000.0)
            assert pool.run_target_us == 9000.0
            knobs["readahead_window_batches"].set(2.0)
            assert ra.window_batches == 2
        finally:
            ctx.close()

    def test_disabled_readahead_has_no_knob(self):
        from strom.tune.knobs import standard_knobs

        ctx = StromContext(_cfg())
        try:
            ctx.register_tunable("readahead", _RA(0))
            names = {k.name for k in standard_knobs(ctx)}
            assert "readahead_window_batches" not in names
        finally:
            ctx.close()

    def test_profile_round_trip_clamps_and_ignores_unknown(self, tmp_path):
        from strom.tune import Autotuner, Profile
        from strom.tune.knobs import standard_knobs

        ctx = StromContext(_cfg())
        try:
            pool, ra = _Pool(), _RA(4)
            ctx.register_tunable("decode_pool", pool)
            ctx.register_tunable("readahead", ra)
            knobs = [k for k in standard_knobs(ctx)
                     if k.name in ("decode_run_target_us",
                                   "readahead_window_batches")]
            tuner = Autotuner(knobs, lambda: {"objective": 1.0})
            path = str(tmp_path / "profile.json")
            Profile("arm", {"decode_run_target_us": 250.0,  # below lo
                            "readahead_window_batches": 3.0,
                            "gone_knob": 7.0}).save(path)
            applied = tuner.apply_profile(Profile.load(path))
            assert applied == 2  # the unknown name is skipped, not fatal
            assert pool.run_target_us == 500.0  # clamped to the live lo
            assert ra.window_batches == 3
        finally:
            ctx.close()


def test_stall_weighted_metrics():
    from strom.tune import stall_weighted_metrics

    def base():
        return {"objective": 100.0,
                "stall_ingest_wait_us_per_s": 250_000.0,
                "stall_compute_us_per_s": 750_000.0}

    m = stall_weighted_metrics(base, wait_weight=0.5)()
    assert m["ingest_wait_share"] == 0.25
    assert m["objective"] == pytest.approx(100.0 * (1 - 0.5 * 0.25))
    # without the rates the wrapper is a pass-through
    m2 = stall_weighted_metrics(lambda: {"objective": 7.0})()
    assert m2["objective"] == 7.0 and "ingest_wait_share" not in m2


def test_readahead_window_fn_arity():
    """Zero-arg window fns (every pre-ISSUE-19 caller) keep working; fns
    taking a count receive the live window_batches value."""
    from strom.delivery.hotcache import Readahead

    ctx = StromContext(_cfg())
    ras = []
    try:
        ra0 = Readahead(ctx, lambda: [])
        ras.append(ra0)
        assert ra0._fn_takes_n is False
        got = []
        ra1 = Readahead(ctx, lambda n: got.append(n) or [],
                        window_batches=4)
        ras.append(ra1)
        assert ra1._fn_takes_n is True
        assert ra1.window_batches == 4
    finally:
        for ra in ras:
            ra.close()
        ctx.close()
