"""Engine-API conformance for the async vectored path (ISSUE 5 satellite):
submit_vectored / poll / drain semantics and completion-count accounting,
parametrized over EVERY Engine implementation — the python thread-pool
engine, the native io_uring engine, and the multi-ring engine in both its
single-ring and fan-out shapes. One behavioral contract, three machines."""

import threading
import time

import numpy as np
import pytest

from strom.config import StromConfig
from strom.engine.base import EngineError

MiB = 1024 * 1024


def _uring_ok() -> bool:
    from strom.engine.uring_engine import uring_available

    return uring_available()


@pytest.fixture(params=["python", "uring", "multi", "multi2"])
def any_engine(request):
    """One instance of every Engine subclass/shape (uring-backed shapes
    skip where the sandbox refuses io_uring_setup)."""
    cfg = StromConfig(queue_depth=8, num_buffers=16)
    if request.param == "python":
        from strom.engine.python_engine import PythonEngine

        eng = PythonEngine(cfg)
    elif request.param == "uring":
        if not _uring_ok():
            pytest.skip("io_uring unavailable in this sandbox")
        from strom.engine.uring_engine import UringEngine

        eng = UringEngine(cfg)
    else:
        if not _uring_ok():
            pytest.skip("io_uring unavailable in this sandbox")
        from strom.engine.multi import MultiRingEngine

        eng = MultiRingEngine(cfg, rings=2 if request.param == "multi2" else 1)
    yield eng
    eng.close()


def _chunks_for(eng, path: str, nbytes: int, n: int):
    fi = eng.register_file(path)
    per = nbytes // n // 512 * 512
    return [(fi, i * per, i * per, per) for i in range(n)], n * per


class TestSubmitPollDrain:
    def test_integrity_and_exactly_once_accounting(self, any_engine,
                                                   data_file):
        """Every chunk completes exactly once (the completion-count
        contract), bytes land where the plan says, drain returns the sum."""
        path, golden = data_file
        chunks, total = _chunks_for(any_engine, path, 4 * MiB, 16)
        dest = np.zeros(total, dtype=np.uint8)
        tok = any_engine.submit_vectored(chunks, dest)
        seen: list[int] = []
        while not tok.done:
            for c in any_engine.poll(tok, min_completions=1):
                assert c.result == chunks[c.index][3]
                seen.append(c.index)
        assert sorted(seen) == list(range(16))  # exactly once each
        assert any_engine.drain(tok) == total
        np.testing.assert_array_equal(dest, golden[:total])
        assert any_engine.in_flight() == 0

    def test_multi_piece_chunks_complete_once(self, any_engine, data_file):
        """A chunk larger than block_size (several engine ops) still
        surfaces as ONE completion, when its last piece lands."""
        path, golden = data_file
        fi = any_engine.register_file(path)
        ln = 1 * MiB  # 8 block-size pieces at the 128KiB default
        chunks = [(fi, 0, 0, ln), (fi, ln, ln, ln)]
        dest = np.zeros(2 * ln, dtype=np.uint8)
        tok = any_engine.submit_vectored(chunks, dest)
        seen = []
        while not tok.done:
            seen.extend(any_engine.poll(tok, min_completions=1))
        assert sorted(c.index for c in seen) == [0, 1]
        assert all(c.result == ln for c in seen)
        assert any_engine.drain(tok) == 2 * ln
        np.testing.assert_array_equal(dest, golden[: 2 * ln])

    def test_drain_without_polling(self, any_engine, data_file):
        path, golden = data_file
        chunks, total = _chunks_for(any_engine, path, 2 * MiB, 4)
        dest = np.zeros(total, dtype=np.uint8)
        tok = any_engine.submit_vectored(chunks, dest)
        assert any_engine.drain(tok) == total
        np.testing.assert_array_equal(dest, golden[:total])

    def test_poll_zero_never_blocks(self, any_engine, data_file):
        path, _ = data_file
        chunks, total = _chunks_for(any_engine, path, 4 * MiB, 8)
        dest = np.zeros(total, dtype=np.uint8)
        tok = any_engine.submit_vectored(chunks, dest)
        t0 = time.monotonic()
        any_engine.poll(tok, min_completions=0)
        assert time.monotonic() - t0 < 1.0
        any_engine.drain(tok)

    def test_empty_gather(self, any_engine):
        dest = np.zeros(0, dtype=np.uint8)
        tok = any_engine.submit_vectored([], dest)
        assert tok.done
        assert any_engine.poll(tok, min_completions=0) == []
        assert any_engine.drain(tok) == 0

    def test_inflight_peak_reported(self, any_engine, data_file):
        path, _ = data_file
        chunks, total = _chunks_for(any_engine, path, 4 * MiB, 16)
        dest = np.zeros(total, dtype=np.uint8)
        tok = any_engine.submit_vectored(chunks, dest)
        any_engine.drain(tok)
        assert 1 <= tok.inflight_peak

    def test_sequential_tokens_reuse_engine(self, any_engine, data_file):
        """A drained token leaves the engine clean for the next gather —
        no stale tags, no leaked queue depth."""
        path, golden = data_file
        for _ in range(3):
            chunks, total = _chunks_for(any_engine, path, 1 * MiB, 4)
            dest = np.zeros(total, dtype=np.uint8)
            tok = any_engine.submit_vectored(chunks, dest)
            assert any_engine.drain(tok) == total
            np.testing.assert_array_equal(dest, golden[:total])
        assert any_engine.in_flight() == 0


class TestErrorsAndCancellation:
    def test_short_read_surfaces_after_full_drain(self, any_engine,
                                                  data_file):
        """A chunk past EOF errors the gather — raised by drain only after
        every in-flight piece retired (in_flight() == 0 at raise time)."""
        path, _ = data_file
        fi = any_engine.register_file(path)
        import os as _os

        size = _os.stat(path).st_size
        ok = 512 * 1024
        chunks = [(fi, 0, 0, ok), (fi, size - 4096, ok, 1 * MiB)]
        dest = np.zeros(ok + 1 * MiB, dtype=np.uint8)
        tok = any_engine.submit_vectored(chunks, dest)
        with pytest.raises(EngineError):
            any_engine.drain(tok)
        assert any_engine.in_flight() == 0

    def test_error_chunk_completion_is_negative(self, any_engine,
                                                data_file):
        path, _ = data_file
        fi = any_engine.register_file(path)
        import os as _os

        size = _os.stat(path).st_size
        chunks = [(fi, size - 4096, 0, 1 * MiB)]  # extends past EOF
        dest = np.zeros(1 * MiB, dtype=np.uint8)
        tok = any_engine.submit_vectored(chunks, dest)
        seen = []
        while not tok.done:
            seen.extend(any_engine.poll(tok, min_completions=1))
        assert any(c.result < 0 for c in seen)
        with pytest.raises(EngineError):
            any_engine.drain(tok)

    def test_cancel_reaps_everything(self, any_engine, data_file):
        path, _ = data_file
        chunks, total = _chunks_for(any_engine, path, 4 * MiB, 16)
        dest = np.zeros(total, dtype=np.uint8)
        tok = any_engine.submit_vectored(chunks, dest)
        any_engine.cancel(tok)
        assert tok.cancelled
        assert any_engine.in_flight() == 0
        with pytest.raises(EngineError):
            any_engine.poll(tok)

    def test_close_cancels_live_token(self, any_engine, data_file):
        """Cancellation-on-close: closing an engine with a token in flight
        reaps every completion (no worker/kernel write outlives close) and
        marks the token cancelled instead of hanging or leaking."""
        path, _ = data_file
        chunks, total = _chunks_for(any_engine, path, 4 * MiB, 16)
        dest = np.zeros(total, dtype=np.uint8)
        tok = any_engine.submit_vectored(chunks, dest)
        t = threading.Thread(target=any_engine.close)
        t.start()
        t.join(timeout=30)
        assert not t.is_alive(), "close() hung on a live token"
        assert tok.cancelled


@pytest.fixture()
def py_multi(monkeypatch):
    """A 2-ring MultiRingEngine with PYTHON-engine children: the _FanToken
    routing/merge/cancel state machine runs even where the sandbox refuses
    io_uring_setup (the uring-parametrized tests above cover ring-native
    behavior when it exists)."""
    import strom.engine.multi as multi_mod
    from strom.engine.python_engine import PythonEngine

    class _PyChild(PythonEngine):
        def __init__(self, config, variant=""):
            super().__init__(config)

    import strom.engine.uring_engine as ue

    monkeypatch.setattr(ue, "UringEngine", _PyChild)
    eng = multi_mod.MultiRingEngine(StromConfig(queue_depth=8,
                                                num_buffers=16), rings=2)
    yield eng
    eng.close()


class TestFanTokenLogic:
    def test_two_file_fanout_integrity(self, py_multi, tmp_path, rng):
        datas, fis = [], []
        for i in range(2):
            d = rng.integers(0, 256, 1 * MiB, dtype=np.uint8)
            p = tmp_path / f"f{i}.bin"
            d.tofile(p)
            datas.append(d)
            fis.append(py_multi.register_file(str(p)))
        half = 512 * 1024
        chunks = [(fis[0], 0, 0, half), (fis[1], 0, half, half),
                  (fis[0], half, 2 * half, half), (fis[1], half, 3 * half,
                                                   half)]
        dest = np.zeros(4 * half, dtype=np.uint8)
        tok = py_multi.submit_vectored(chunks, dest)
        seen = []
        while not tok.done:
            seen.extend(py_multi.poll(tok, min_completions=1))
        assert sorted(c.index for c in seen) == [0, 1, 2, 3]
        assert all(c.result == half for c in seen)
        assert py_multi.drain(tok) == 4 * half
        np.testing.assert_array_equal(dest[:half], datas[0][:half])
        np.testing.assert_array_equal(dest[half: 2 * half], datas[1][:half])
        np.testing.assert_array_equal(dest[2 * half: 3 * half],
                                      datas[0][half:])
        np.testing.assert_array_equal(dest[3 * half:], datas[1][half:])
        # ring locks released: a blocking gather runs fine afterwards
        dest2 = np.zeros(half, dtype=np.uint8)
        assert py_multi.read_vectored([(fis[0], 0, 0, half)], dest2) == half

    def test_single_file_rides_one_ring(self, py_multi, data_file):
        path, golden = data_file
        fi = py_multi.register_file(path)
        chunks = [(fi, i * 256 * 1024, i * 256 * 1024, 256 * 1024)
                  for i in range(8)]
        dest = np.zeros(2 * MiB, dtype=np.uint8)
        tok = py_multi.submit_vectored(chunks, dest)
        assert py_multi.drain(tok) == 2 * MiB
        np.testing.assert_array_equal(dest, golden[: 2 * MiB])

    def test_cancel_releases_ring_locks(self, py_multi, tmp_path, rng):
        datas, fis = [], []
        for i in range(2):
            d = rng.integers(0, 256, 1 * MiB, dtype=np.uint8)
            p = tmp_path / f"c{i}.bin"
            d.tofile(p)
            datas.append(d)
            fis.append(py_multi.register_file(str(p)))
        half = 512 * 1024
        chunks = [(fis[0], 0, 0, half), (fis[1], 0, half, half)]
        dest = np.zeros(2 * half, dtype=np.uint8)
        tok = py_multi.submit_vectored(chunks, dest)
        py_multi.cancel(tok)
        assert tok.cancelled
        assert py_multi.in_flight() == 0
        with pytest.raises(EngineError):
            py_multi.drain(tok)
        # both ring locks must be free again
        dest2 = np.zeros(half, dtype=np.uint8)
        assert py_multi.read_vectored([(fis[0], 0, 0, half)], dest2) == half
        assert py_multi.read_vectored([(fis[1], 0, 0, half)], dest2) == half

    def test_close_with_live_fan_token(self, py_multi, tmp_path, rng):
        d = rng.integers(0, 256, 2 * MiB, dtype=np.uint8)
        p = tmp_path / "x.bin"
        d.tofile(p)
        f0 = py_multi.register_file(str(p))
        chunks = [(f0, i * 256 * 1024, i * 256 * 1024, 256 * 1024)
                  for i in range(8)]
        dest = np.zeros(2 * MiB, dtype=np.uint8)
        tok = py_multi.submit_vectored(chunks, dest)
        t = threading.Thread(target=py_multi.close)
        t.start()
        t.join(timeout=30)
        assert not t.is_alive(), "multi close() hung on a live fan token"
        assert tok.cancelled


class TestMultiRingFanout:
    def test_fanout_maps_indices_back(self, tmp_path, rng):
        """A two-file gather on a 2-ring engine fans per file; completions
        still name the CALLER's chunk indices."""
        if not _uring_ok():
            pytest.skip("io_uring unavailable in this sandbox")
        from strom.engine.multi import MultiRingEngine

        datas, paths = [], []
        for i in range(2):
            d = rng.integers(0, 256, 1 * MiB, dtype=np.uint8)
            p = tmp_path / f"m{i}.bin"
            d.tofile(p)
            datas.append(d)
            paths.append(str(p))
        eng = MultiRingEngine(StromConfig(queue_depth=8, num_buffers=16),
                              rings=2)
        try:
            fis = [eng.register_file(p) for p in paths]
            half = 512 * 1024
            chunks = [(fis[0], 0, 0, half), (fis[1], 0, half, half),
                      (fis[0], half, 2 * half, half),
                      (fis[1], half, 3 * half, half)]
            dest = np.zeros(4 * half, dtype=np.uint8)
            tok = eng.submit_vectored(chunks, dest)
            seen = []
            while not tok.done:
                seen.extend(eng.poll(tok, min_completions=1))
            assert sorted(c.index for c in seen) == [0, 1, 2, 3]
            assert eng.drain(tok) == 4 * half
            np.testing.assert_array_equal(dest[:half], datas[0][:half])
            np.testing.assert_array_equal(dest[half: 2 * half],
                                          datas[1][:half])
            np.testing.assert_array_equal(dest[2 * half: 3 * half],
                                          datas[0][half:])
            np.testing.assert_array_equal(dest[3 * half:], datas[1][half:])
        finally:
            eng.close()
