"""Multi-tenant I/O scheduler (ISSUE 7 tentpole — strom/sched/).

Fairness and starvation contracts, deterministically:

- the weighted fair drain (deficit round-robin / min-virtual-time) is
  white-box-sequenced without threads, so the grant ORDER is asserted,
  not sampled;
- a greedy tenant (deep queue, large sliced ops) vs a light interactive
  tenant on one exclusive engine: the light tenant's queue wait is
  BOUNDED by ~a slice, never by the greedy tenant's whole backlog;
- budgets (token buckets: fake-clock unit tests + a real-time
  enforcement pass through the scheduler), slab-pool admission control,
  hot-cache partitions, /tenants HTTP lifecycle, the
  release-at-gather-drain engine-lock fix, and concurrent-pipeline
  bit-identity against solo runs.
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from strom.config import StromConfig
from strom.sched.budget import AdmissionGate, TokenBucket
from strom.sched.scheduler import SCHED_FIELDS, IoScheduler, _Waiter
from strom.sched.tenant import PRIORITY_ORDER


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------------ token bucket
class TestTokenBucket:
    def test_rate_enforced(self):
        clk = FakeClock()
        b = TokenBucket(100.0, 50.0, clock=clk)  # 100/s, burst 50
        assert b.peek(50) == 0.0
        b.take(50)
        # empty: 30 units need 0.3s
        assert b.peek(30) == pytest.approx(0.3)
        clk.advance(0.3 + 1e-9)
        assert b.peek(30) == 0.0

    def test_burst_caps_refill(self):
        clk = FakeClock()
        b = TokenBucket(100.0, 50.0, clock=clk)
        clk.advance(100)  # long idle: tokens cap at burst, not 10k
        assert b.tokens == pytest.approx(50.0)

    def test_oversized_op_runs_on_debt(self):
        """An op larger than the burst must not deadlock: it waits for a
        full bucket, then drives the balance negative (debt) so the
        long-run rate still holds."""
        clk = FakeClock()
        b = TokenBucket(100.0, 50.0, clock=clk)
        assert b.peek(500) == 0.0  # full bucket: a jumbo op may start
        b.take(500)
        assert b.tokens == pytest.approx(-450.0)
        # the debt gates the NEXT op until the bucket recovers
        assert b.peek(1) > 4.0
        clk.advance(5.0)
        assert b.peek(1) == 0.0

    def test_unlimited(self):
        b = TokenBucket(0)
        assert b.unlimited and b.peek(1 << 40) == 0.0
        b.take(1 << 40)  # no-op


# --------------------------------------------------------- admission gate
class FakePool:
    def __init__(self, max_bytes=1000):
        self.max_bytes = max_bytes
        self.in_use_bytes = 0
        self.hooks = []

    def add_change_hook(self, fn):
        self.hooks.append(fn)

    def set_in_use(self, n):
        self.in_use_bytes = n
        for fn in self.hooks:
            fn()


class TestAdmissionGate:
    def test_room_below_high_water(self):
        pool = FakePool(1000)
        g = AdmissionGate(pool, 0.9)
        pool.set_in_use(800)
        assert g.admit(100)  # 900 == limit: fits
        assert g.waits == 0

    def test_queues_under_pressure_until_release(self):
        from strom.utils.stats import global_stats

        pool = FakePool(1000)
        g = AdmissionGate(pool, 0.9)
        pool.set_in_use(850)
        waits0 = global_stats.counter("slab_pool_admission_waits").value
        done = threading.Event()
        ok = []

        def admit():
            ok.append(g.admit(100, timeout_s=10.0))
            done.set()

        t = threading.Thread(target=admit, daemon=True)
        t.start()
        assert not done.wait(0.15), "over-high-water admit must queue"
        pool.set_in_use(100)  # release: the pool hook wakes the gate
        assert done.wait(5.0)
        assert ok == [True]
        assert g.waits == 1
        assert global_stats.counter(
            "slab_pool_admission_waits").value == waits0 + 1

    def test_timeout_returns_false(self):
        pool = FakePool(1000)
        g = AdmissionGate(pool, 0.9)
        pool.set_in_use(950)
        t0 = time.monotonic()
        assert not g.admit(200, timeout_s=0.2)
        assert time.monotonic() - t0 < 2.0

    def test_disabled_without_pool(self):
        g = AdmissionGate(None, 0.9)
        assert g.admit(1 << 40)


# ------------------------------------------------------ fair drain (order)
class StubEngine:
    """Engine stand-in for scheduler-order tests: read_vectored sleeps
    per byte so service time is controllable."""

    name = "stub"
    concurrent_gathers = False

    def __init__(self, s_per_byte=0.0):
        self.s_per_byte = s_per_byte
        self.calls: list = []

    def read_vectored(self, chunks, dest, *, retries=1):
        n = sum(ln for (_, _, _, ln) in chunks)
        if self.s_per_byte:
            time.sleep(n * self.s_per_byte)
        self.calls.append(n)
        return n

    def set_scope(self, scope):
        pass


def _mk_sched(engine=None, **cfg_kw) -> IoScheduler:
    cfg = StromConfig(sched_enabled=True, **cfg_kw)
    return IoScheduler(engine or StubEngine(), cfg)


def _enqueue(sched, tenant, nbytes, priority=None):
    """White-box: queue a waiter without blocking a thread on it (the
    scheduler's own enqueue path, so the vtime-baseline rule applies)."""
    t = sched.resolve(tenant)
    prio = PRIORITY_ORDER[priority or t.priority]
    w = _Waiter(t, nbytes, prio, sched._clock())
    with sched._cond:
        sched._enqueue_locked(w)
    return w


def _drain_order(sched) -> list:
    """Repeatedly dispatch + release, recording tenant grant order."""
    order = []
    with sched._cond:
        while True:
            sched._dispatch_locked()
            w = sched._current
            if w is None:
                break
            order.append(w.tenant.name)
            w.tenant.active -= 1
            sched._current = None
    return order


class TestFairDrain:
    def test_strict_priority_classes(self):
        """interactive > training > background, regardless of enqueue
        order or deficit state."""
        s = _mk_sched()
        s.register("bg", priority="background")
        s.register("train", priority="training")
        s.register("live", priority="interactive")
        _enqueue(s, "bg", 100)
        _enqueue(s, "train", 100)
        _enqueue(s, "live", 100)
        _enqueue(s, "bg", 100)
        _enqueue(s, "live", 100)
        order = _drain_order(s)
        assert order == ["live", "live", "train", "bg", "bg"]

    def test_weighted_fair_within_class(self):
        """DRR in its min-virtual-time form: a weight-2 tenant drains ~2
        bytes for every 1 of a weight-1 tenant when both stay backlogged."""
        s = _mk_sched()
        s.register("heavy", weight=2)
        s.register("light", weight=1)
        for _ in range(8):
            _enqueue(s, "heavy", 100)
        for _ in range(4):
            _enqueue(s, "light", 100)
        order = _drain_order(s)
        # by the time light's 4 ops drained, heavy must have ~2x served
        cut = max(i for i, n in enumerate(order) if n == "light")
        heavy_before = sum(1 for n in order[:cut] if n == "heavy")
        assert 6 <= heavy_before <= 8, order

    def test_light_tenant_never_waits_out_backlog(self):
        """The queued-op deficit keeps a light tenant at the head: after
        every grant of the greedy tenant, a queued light op goes next."""
        s = _mk_sched()
        s.register("greedy")
        s.register("light")
        for _ in range(6):
            _enqueue(s, "greedy", 1000)
        _enqueue(s, "light", 10)
        order = _drain_order(s)
        # the light op drains within the first two grants, not after 6
        assert "light" in order[:2], order

    def test_idle_tenant_joins_at_baseline(self):
        """A tenant idle through N grants must not bank unbounded credit
        and then monopolize (the vtime baseline rule)."""
        s = _mk_sched()
        s.register("a")
        s.register("b")
        for _ in range(4):
            _enqueue(s, "a", 100)
        assert _drain_order(s) == ["a"] * 4
        # b was idle the whole time; now both enqueue — b must not get
        # 4 back-to-back catch-up grants
        for _ in range(3):
            _enqueue(s, "a", 100)
            _enqueue(s, "b", 100)
        order = _drain_order(s)
        assert order[:2] != ["b", "b"], order

    def test_throttled_class_yields_engine_to_lower_class(self):
        """Strict priority orders RUNNABLE work: when every queued tenant
        of the top class is budget-throttled, ready lower-class work
        drains instead of the engine idling (work conservation) — and the
        throttled class is picked first again once its budget refills."""
        clk = FakeClock()
        s = IoScheduler(StubEngine(), StromConfig(sched_enabled=True),
                        clock=clk)
        s.register("live", priority="interactive",
                   byte_rate=1_000_000, byte_burst=100)
        s.register("bg", priority="background")
        for _ in range(3):
            _enqueue(s, "live", 100)
        for _ in range(4):
            _enqueue(s, "bg", 100)
        order = _drain_order(s)
        # live's first op rides the burst; its refill window (the fake
        # clock is frozen = forever) must not stall bg's ready ops
        assert order == ["live"] + ["bg"] * 4, order
        assert len(s.tenant("live").queue) == 2
        # budget refilled: higher class leads again (the burst covers one
        # op per refill window)
        for _ in range(2):
            clk.advance(1.0)
            assert _drain_order(s) == ["live"]
        assert not s.tenant("live").queue


# ------------------------------------------- starvation bound (integration)
class TestStarvationBound:
    def test_interactive_bounded_behind_greedy_slices(self):
        """A greedy tenant drains a deep queue of large sliced gathers;
        a light INTERACTIVE tenant's ops must each wait ~one slice, not
        the greedy backlog. This is the tentpole's acceptance shape on a
        stub engine with deterministic service time."""
        eng = StubEngine(s_per_byte=0.002 / 1000)  # 2ms per 1000-byte slice
        s = _mk_sched(eng, sched_slice_bytes=1000)
        s.register("greedy", priority="training")
        s.register("live", priority="interactive")
        greedy_chunks = [(0, 0, i * 1000, 1000) for i in range(120)]
        stop = threading.Event()
        waits: list[float] = []

        def greedy():
            while not stop.is_set():
                s.read_chunks(greedy_chunks, None, tenant="greedy")

        g = threading.Thread(target=greedy, daemon=True)
        g.start()
        time.sleep(0.02)  # greedy is mid-backlog
        try:
            for _ in range(10):
                t0 = time.monotonic()
                with s.grant("live", 10):
                    pass
                waits.append(time.monotonic() - t0)
                time.sleep(0.005)
        finally:
            stop.set()
            g.join(timeout=10)
        # greedy's full gather is 120 slices x 2ms = 240ms; a light op may
        # wait out the slice in flight (~2ms) plus scheduling jitter, but
        # NEVER a whole gather. 60ms is a >10x jitter margin that still
        # proves slice-granular preemption.
        assert max(waits) < 0.06, waits
        live = s.tenant("live")
        assert live.granted_ops == 10

    def test_exclusive_grants_serialize(self):
        """Two grants never overlap on an exclusive engine (the scheduler
        IS the engine lock now — this is its mutual-exclusion contract)."""
        s = _mk_sched()
        inside = []
        overlap = []

        def worker(name):
            for _ in range(20):
                with s.grant(name, 10):
                    inside.append(name)
                    if len(inside) > 1:
                        overlap.append(tuple(inside))
                    time.sleep(0.0005)
                    inside.remove(name)

        ts = [threading.Thread(target=worker, args=(n,), daemon=True)
              for n in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert not overlap


# ------------------------------------------------------------------ budgets
class TestBudgetEnforcement:
    def test_byte_budget_throttles_and_counts(self):
        """A tenant with a byte/s budget: the first grant rides the burst,
        later grants wait for refill — elapsed time reflects the rate and
        sched_throttle_waits ticks."""
        s = _mk_sched()
        s.register("metered", byte_rate=1_000_000, byte_burst=50_000)
        t0 = time.monotonic()
        for _ in range(3):
            with s.grant("metered", 50_000):
                pass
        dt = time.monotonic() - t0
        # grants 2 and 3 each wait ~50ms of refill
        assert dt >= 0.08, dt
        assert s.tenant("metered").throttle_waits >= 2

    def test_unbudgeted_tenant_not_throttled_by_neighbor(self):
        s = _mk_sched()
        s.register("metered", byte_rate=1000, byte_burst=100)
        s.register("free")
        with s.grant("metered", 100):
            pass
        t0 = time.monotonic()
        for _ in range(5):
            with s.grant("free", 10_000):
                pass
        assert time.monotonic() - t0 < 0.5
        assert s.tenant("free").throttle_waits == 0

    def test_throttle_waits_counts_episodes_not_passes(self):
        """sched_throttle_waits is a SCHED_FIELDS bench column compared
        round-over-round: it must count throttled grant EPISODES, not how
        many dispatch passes (or 50ms poll ticks) happened to observe the
        same waiting op — otherwise its value scales with unrelated
        tenants' grant rates instead of budget pressure."""
        clk = FakeClock()
        s = IoScheduler(StubEngine(), StromConfig(sched_enabled=True),
                        clock=clk)
        s.register("metered", byte_rate=1_000_000, byte_burst=100)
        _enqueue(s, "metered", 100)
        _enqueue(s, "metered", 100)
        with s._cond:
            s._dispatch_locked()  # grant 1 rides the burst
            s._current.tenant.active -= 1
            s._current = None
            for _ in range(10):  # many passes observe one episode
                s._dispatch_locked()
        assert s.tenant("metered").throttle_waits == 1

    def test_iops_budget(self):
        s = _mk_sched()
        s.register("m", iops=50)  # burst 50 ops, 50/s refill
        t0 = time.monotonic()
        for _ in range(52):
            with s.grant("m", 1):
                pass
        assert time.monotonic() - t0 >= 0.03  # ops 51+ waited on refill


# --------------------------------------------------- scheduler-context glue
class TestContextIntegration:
    def test_read_through_scheduler_bit_identical(self, tmp_path):
        """sched on vs off: byte-identical pread results (slicing moves
        lock boundaries, never bytes)."""
        from strom.delivery.core import StromContext

        data = np.random.default_rng(0).integers(
            0, 256, 2 * 1024 * 1024 + 123, dtype=np.uint8)
        p = str(tmp_path / "f.bin")
        data.tofile(p)
        outs = []
        for on in (True, False):
            cfg = StromConfig(engine="python", sched_enabled=on,
                              sched_slice_bytes=256 * 1024)
            ctx = StromContext(cfg)
            try:
                outs.append(bytes(ctx.pread(p)))
            finally:
                ctx.close()
        assert outs[0] == outs[1] == data.tobytes()

    def test_tenant_accounting_lands_scoped(self, tmp_path):
        """A tenant-labeled read surfaces sched_granted_bytes in the
        tenant's labeled series AND the unlabeled aggregate (PR 6 rule)."""
        from strom.delivery.core import StromContext
        from strom.utils.stats import global_stats

        data = np.zeros(512 * 1024, dtype=np.uint8)
        p = str(tmp_path / "z.bin")
        data.tofile(p)
        ctx = StromContext(StromConfig(engine="python"))
        try:
            before = global_stats.scoped(
                tenant="acct").counter("sched_granted_bytes").value
            ctx.register_tenant("acct", priority="interactive")
            ctx.pread(p, tenant="acct")
            scoped = global_stats.scoped(
                tenant="acct").counter("sched_granted_bytes").value
            assert scoped - before >= data.nbytes
        finally:
            ctx.close()

    def test_engine_exclusive_helper(self, tmp_path):
        from strom.delivery.core import StromContext

        ctx = StromContext(StromConfig(engine="python"))
        try:
            with ctx.engine_exclusive(123):
                pass
            assert ctx.scheduler.tenant().granted_ops >= 1
        finally:
            ctx.close()


# --------------------------------------- engine-lock release at drain (sat.)
class TestReleaseAtDrain:
    def test_streaming_gather_releases_engine_at_drain(self, tmp_path):
        """ISSUE 7 satellite: once every piece of a streamed gather has
        retired (token drained), the engine grant releases IMMEDIATELY —
        a concurrent blocking read must proceed while the gather sits
        un-finish()ed, matching the streamed pipeline path's release
        point."""
        from strom.delivery.core import StromContext
        from strom.delivery.shard import Segment

        data = np.random.default_rng(3).integers(
            0, 256, 1024 * 1024, dtype=np.uint8)
        p = str(tmp_path / "g.bin")
        data.tofile(p)
        ctx = StromContext(StromConfig(engine="python"))
        try:
            dest = np.empty(data.nbytes, dtype=np.uint8)
            g = ctx.stream_segments(p, [Segment(0, 0, data.nbytes)], dest)
            while not g.done:
                g.poll(min_completions=1, timeout_s=5.0)
            # token drained, finish() NOT yet called: the engine must be
            # free for another tenant right now
            done = threading.Event()

            def other():
                ctx.pread(p, length=4096)
                done.set()

            threading.Thread(target=other, daemon=True).start()
            assert done.wait(5.0), \
                "engine grant still held after gather drain"
            assert g.finish() == data.nbytes
            np.testing.assert_array_equal(dest, data)
        finally:
            ctx.close()


# ---------------------------------------------------- hot-cache partitions
class TestCachePartitions:
    def _cache(self, budget=1 << 20):
        from strom.delivery.hotcache import HotCache

        return HotCache(budget, admit="always", block_bytes=4096)

    def test_partition_caps_tenant(self):
        c = self._cache()
        c.set_partition("a", 8192)
        blob = np.zeros(4096, dtype=np.uint8)
        assert c.admit("k", 0, 4096, blob, tenant="a") == 4096
        assert c.admit("k", 4096, 8192, blob, tenant="a") == 4096
        # third admit: over the partition — evicts a's OWN oldest entry
        assert c.admit("k", 8192, 12288, blob, tenant="a") == 4096
        assert c.partitions()["a"]["bytes"] <= 8192
        # the evicted range misses now; the newest two still hit
        hits, misses, pins = c.lookup("k", 0, 12288)
        c.unpin(pins)
        assert (0, 4096) in misses

    def test_partition_never_displaces_other_tenant(self):
        c = self._cache()
        c.set_partition("a", 4096)
        blob = np.zeros(4096, dtype=np.uint8)
        assert c.admit("kb", 0, 4096, blob, tenant="b") == 4096
        assert c.admit("ka", 0, 4096, blob, tenant="a") == 4096
        # a over-cap: must self-evict or refuse, b's entry stays
        c.admit("ka", 4096, 8192, blob, tenant="a")
        hits, _, pins = c.lookup("kb", 0, 4096)
        c.unpin(pins)
        assert hits, "tenant b's entry was displaced by tenant a"

    def test_oversized_entry_refused(self):
        c = self._cache()
        c.set_partition("a", 4096)
        blob = np.zeros(64 * 1024, dtype=np.uint8)
        assert c.admit("k", 0, blob.nbytes, blob, tenant="a") == 0

    def test_register_tenant_carves_partition(self, tmp_path):
        from strom.delivery.core import StromContext

        cfg = StromConfig(engine="python", hot_cache_bytes=1 << 20)
        ctx = StromContext(cfg)
        try:
            ctx.register_tenant("carved", hot_cache_bytes=64 * 1024)
            assert ctx.hot_cache.partitions()["carved"]["max_bytes"] \
                == 64 * 1024
            # re-registering returns the live handle UNCHANGED and must
            # not half-apply the new config (scheduler keeps the old
            # priority/budgets, so the cache partition stays too)
            t = ctx.register_tenant("carved", priority="interactive",
                                    hot_cache_bytes=1 << 20)
            assert t.priority == "training"
            assert ctx.hot_cache.partitions()["carved"]["max_bytes"] \
                == 64 * 1024
        finally:
            ctx.close()

    def test_warm_admits_charge_owning_tenant(self, tmp_path):
        """Readahead warming must charge the OWNING pipeline's cache
        partition — a force-admit with no tenant would bypass the
        carve-outs and displace other tenants' hot sets through the
        shared-budget LRU."""
        from strom.delivery.core import StromContext
        from strom.delivery.shard import Segment

        path = str(tmp_path / "warm.bin")
        data = os.urandom(128 * 1024)
        with open(path, "wb") as f:
            f.write(data)
        cfg = StromConfig(engine="python", hot_cache_bytes=1 << 20,
                          hot_cache_admit="always")
        ctx = StromContext(cfg)
        try:
            ctx.register_tenant("owner", hot_cache_bytes=512 * 1024)
            warmed = ctx.warm(path, [Segment(0, 0, len(data))],
                              tenant="owner")
            assert warmed == len(data)
            assert ctx.hot_cache.partitions()["owner"]["bytes"] \
                == len(data)
        finally:
            ctx.close()


# ------------------------------------------------- occupancy gauges (sat.)
class TestSlabGauges:
    def test_in_use_tracks_acquire_release(self):
        from strom.delivery.buffers import SlabPool
        from strom.utils.stats import global_stats

        pool = SlabPool(4 << 20)
        a = pool.acquire(100_000)
        assert pool.in_use_bytes > 0
        assert pool.stats()["slab_in_use_bytes"] == pool.in_use_bytes
        assert global_stats.gauge("slab_pool_bytes_in_use").value \
            == pool.in_use_bytes
        pool.release(a)
        assert pool.in_use_bytes == 0
        assert global_stats.gauge("slab_pool_bytes_in_use").value == 0

    def test_alloc_failure_rolls_back_occupancy(self, monkeypatch):
        """A failed allocation must hand its occupancy charge back: a
        leaked charge would permanently inflate slab_pool_bytes_in_use and
        wedge the admission gate past high-water on phantom bytes."""
        from strom.delivery import buffers
        from strom.utils.stats import global_stats

        pool = buffers.SlabPool(4 << 20)

        def boom(*a, **k):
            raise MemoryError("mmap ENOMEM")

        monkeypatch.setattr(buffers, "alloc_aligned", boom)
        with pytest.raises(MemoryError):
            pool.acquire(100_000)
        assert pool.in_use_bytes == 0
        assert pool.mlocked_bytes == 0
        assert global_stats.gauge("slab_pool_bytes_in_use").value == 0
        monkeypatch.undo()
        a = pool.acquire(100_000)  # pool still serviceable
        assert pool.in_use_bytes > 0
        pool.release(a)

    def test_gauges_reach_metrics_exposition(self, tmp_path):
        """ISSUE 7 satellite: the admission-control gauges are scrapeable
        — slab_pool_bytes_in_use on the global registry, admission waits
        and grant counters via the sched section."""
        from strom.delivery.core import StromContext

        data = np.zeros(256 * 1024, dtype=np.uint8)
        p = str(tmp_path / "x.bin")
        data.tofile(p)
        ctx = StromContext(StromConfig(engine="python"), metrics_port=0)
        try:
            ctx.pread(p)  # slab + grant activity
            port = ctx.metrics_server.port
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics").read().decode()
            assert "strom_slab_pool_bytes_in_use" in body
            assert "strom_sched_sched_granted_ops" in body
            assert "strom_sched_slab_pool_admission_waits" in body
        finally:
            ctx.close()


# ----------------------------------------------- concurrent pipelines (acc.)
class TestConcurrentPipelines:
    @pytest.fixture(scope="class")
    def wds(self, tmp_path_factory):
        cv2 = pytest.importorskip("cv2")
        from tests.test_formats import make_wds_shard

        rng = np.random.default_rng(77)
        td = tmp_path_factory.mktemp("mtwds")
        samples = []
        for i in range(16):
            img = rng.integers(0, 256, (48, 56, 3), dtype=np.uint8)
            ok, buf = cv2.imencode(".jpg", img)
            assert ok
            samples.append((f"s{i:04d}", {"jpg": buf.tobytes(),
                                          "cls": str(i % 10).encode()}))
        p = str(td / "mt.tar")
        make_wds_shard(p, samples)
        return [p]

    def test_concurrent_tenants_bit_identical_to_solo(self, wds):
        """The fairness-demo acceptance: two tenant-labeled vision
        pipelines on ONE scheduled context, run CONCURRENTLY, produce
        batches bit-identical to their solo runs (the scheduler moves
        lock boundaries and queue order, never bytes)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from strom.delivery.core import StromContext
        from strom.parallel.mesh import make_mesh
        from strom.pipelines import make_wds_vision_pipeline

        mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
        sharding = NamedSharding(mesh, P("dp", None, None, None))

        def batches(ctx, tenant, n=3):
            pipe = make_wds_vision_pipeline(
                ctx, wds, batch=4, image_size=32, sharding=sharding,
                seed=5, decode_workers=2,
                scope={"pipeline": "resnet", "tenant": tenant})
            try:
                return [np.asarray(next(pipe)[0]) for _ in range(n)]
            finally:
                pipe.close()

        ctx = StromContext(StromConfig(engine="python",
                                       sched_slice_bytes=64 * 1024))
        try:
            solo = {t: batches(ctx, t) for t in ("t0", "t1")}
            got: dict = {}
            errs: list = []

            def run(t):
                try:
                    got[t] = batches(ctx, t)
                except BaseException as e:
                    errs.append(e)

            ts = [threading.Thread(target=run, args=(t,), daemon=True)
                  for t in ("t0", "t1")]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120)
            assert not errs, errs
            for t in ("t0", "t1"):
                assert len(got[t]) == len(solo[t])
                for a, b in zip(got[t], solo[t]):
                    np.testing.assert_array_equal(a, b)
            # per-tenant series visible on the scoped registry
            from strom.utils.stats import global_stats

            scopes = global_stats.scopes_snapshot()
            assert any('tenant="t0"' in k for k in scopes)
            assert any('tenant="t1"' in k for k in scopes)
        finally:
            ctx.close()


# ------------------------------------------------------- /tenants lifecycle
class TestTenantsRoute:
    def test_get_register_drain(self, tmp_path):
        from strom.delivery.core import StromContext

        ctx = StromContext(StromConfig(engine="python"), metrics_port=0)
        try:
            port = ctx.metrics_server.port
            base = f"http://127.0.0.1:{port}/tenants"
            doc = json.load(urllib.request.urlopen(base))
            assert "default" in doc["tenants"]
            req = urllib.request.Request(base, data=json.dumps(
                {"op": "register", "name": "web", "priority": "interactive",
                 "byte_rate": 1e9, "weight": 2}).encode())
            row = json.load(urllib.request.urlopen(req))
            assert row["priority"] == "interactive" and row["weight"] == 2
            doc = json.load(urllib.request.urlopen(base))
            assert doc["tenants"]["web"]["byte_budget"]["rate"] == 1e9
            req = urllib.request.Request(base, data=json.dumps(
                {"op": "drain", "name": "web"}).encode())
            assert json.load(urllib.request.urlopen(req))["drained"] is True
            # bad op → 400, server survives
            req = urllib.request.Request(base, data=b'{"op": "nope"}')
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 400
            # malformed FIELDS are the client's fault too: 400, not 500
            for bad in ({"op": "register", "name": ""},
                        {"op": "register", "name": "x", "weight": "abc"},
                        {"op": "register", "name": "x",
                         "byte_burst": None}):
                req = urllib.request.Request(
                    base, data=json.dumps(bad).encode())
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req)
                assert ei.value.code == 400, bad
        finally:
            ctx.close()


# ------------------------------------------- daemon graceful shutdown (sat.)
class TestDaemonShutdown:
    def test_sigterm_drains_then_flight_chain_runs(self, tmp_path):
        """ISSUE 7 satellite: SIGTERM on daemon mode (1) drains every
        registered tenant (the 'drained' marker with no stuck names — no
        leaked pins/in-flight tokens), (2) only THEN lets the flight
        recorder's chained handler run (bundle on disk), and (3) the exit
        status still says killed-by-SIGTERM (the recorder's re-raise
        contract)."""
        import signal
        import subprocess
        import sys

        fdir = str(tmp_path / "flight")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        p = subprocess.Popen(
            [sys.executable, "-m", "strom.cli", "daemon",
             "--metrics-port", "0", "--engine", "python",
             "--flight-dir", fdir, "--drain-timeout", "5"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=root)
        try:
            ready = p.stdout.readline()
            assert "strom daemon ready" in ready, ready
            port = int(ready.split("port=")[1].split()[0])
            # a real external tenant registers over the daemon surface
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/tenants",
                data=json.dumps({"op": "register", "name": "ext",
                                 "priority": "interactive"}).encode())
            assert json.load(urllib.request.urlopen(req))["name"] == "ext"
            p.send_signal(signal.SIGTERM)
            out, _ = p.communicate(timeout=60)
        finally:
            if p.poll() is None:
                p.kill()
                p.communicate(timeout=10)
        assert "strom daemon drained" in out, out
        assert "stuck=[]" in out, out
        assert p.returncode == -signal.SIGTERM, (p.returncode, out)
        bundles = os.listdir(fdir)
        assert any("sigterm" in b for b in bundles), bundles

    def test_sigint_drains_and_exits_killed_by_signal(self, tmp_path):
        """SIGINT follows the same supervisor contract as SIGTERM: drain
        every tenant first, then die BY the signal (rc = -SIGINT) — not a
        KeyboardInterrupt traceback's rc 1 and not a clean rc 0 that a
        supervisor would read as a successful exit."""
        import signal
        import subprocess
        import sys

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        p = subprocess.Popen(
            [sys.executable, "-m", "strom.cli", "daemon",
             "--metrics-port", "0", "--engine", "python",
             "--drain-timeout", "5"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=root)
        try:
            ready = p.stdout.readline()
            assert "strom daemon ready" in ready, ready
            p.send_signal(signal.SIGINT)
            out, _ = p.communicate(timeout=60)
        finally:
            if p.poll() is None:
                p.kill()
                p.communicate(timeout=10)
        assert "strom daemon drained" in out, out
        assert "stuck=[]" in out, out
        assert p.returncode == -signal.SIGINT, (p.returncode, out)


# ------------------------------------------------ lint covers SCHED_FIELDS
def test_lint_scans_sched_fields():
    """ISSUE 7 satellite: the stats-name lint's *_FIELDS scan must cover
    SCHED_FIELDS — a restyled per-tenant column would fork the bench/
    report contract exactly like a restyled counter."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "lint_stats_names", os.path.join(root, "tools",
                                         "lint_stats_names.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    found, _ = lint.scan_sources(root)
    for name in SCHED_FIELDS:
        norm = name.replace("_", "").lower()
        assert norm in found, f"lint does not scan SCHED_FIELDS ({name})"
