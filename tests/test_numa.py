"""NUMA affinity knobs (SURVEY.md §7.4 hard part #1 / VERDICT.md next #10).

The CI box is UMA (one node, node0) — so these tests exercise the real
syscalls against node 0 where possible and the graceful no-op paths
everywhere else; the multi-socket win itself can't be measured here."""

import os

import numpy as np
import pytest

from strom.delivery.buffers import alloc_aligned
from strom.utils.numa import (NumaAffinity, mbind_array, node_cpus,
                              pin_current_thread, set_irq_affinity)

_HAS_NODE0 = os.path.isdir("/sys/devices/system/node/node0")


class TestPrimitives:
    @pytest.mark.skipif(not _HAS_NODE0, reason="no sysfs NUMA topology")
    def test_node_cpus(self):
        cpus = node_cpus(0)
        assert cpus and all(isinstance(c, int) for c in cpus)
        assert node_cpus(4096) == set()

    @pytest.mark.skipif(not _HAS_NODE0, reason="no sysfs NUMA topology")
    def test_pin_current_thread_roundtrip(self):
        before = os.sched_getaffinity(0)
        try:
            assert pin_current_thread(0)
            assert os.sched_getaffinity(0) <= node_cpus(0)
        finally:
            os.sched_setaffinity(0, before)
        assert not pin_current_thread(4096)  # unknown node -> False, no raise

    @pytest.mark.skipif(not _HAS_NODE0, reason="no sysfs NUMA topology")
    def test_mbind_array(self):
        arr = alloc_aligned(64 * 1024)
        arr[:] = 7
        ok = mbind_array(arr, 0)
        # best-effort contract: either it bound, or the arch table had no
        # syscall number — but it must never corrupt the data
        assert ok in (True, False)
        assert (arr == 7).all()

    def test_irq_affinity_bogus_device(self):
        assert set_irq_affinity("no-such-device-xyz", 0) == 0

    def test_irq_matching_nvme_and_virtio(self):
        """/proc/interrupts names IRQs after the CONTROLLER (nvme0q1,
        virtio0-requests), never the namespace (nvme0n1) or disk (vda)."""
        from strom.utils.numa import _find_irqs, _irq_candidates

        lines = [
            "            CPU0       CPU1\n",
            "  24:          0          0  PCI-MSIX nvme0q0\n",
            "  25:       1234          0  PCI-MSIX nvme0q1\n",
            "  26:          0       5678  PCI-MSIX nvme1q1\n",
            "  27:         42          0  virtio0-requests\n",
            "  28:          0          0  virtio1-config\n",
        ]
        assert _find_irqs(lines, _irq_candidates("nvme0n1")) == [24, 25]
        assert _find_irqs(lines, _irq_candidates("vda", "virtio0")) == [27]
        assert _find_irqs(lines, _irq_candidates("sda")) == []
        # no prefix bleed on dense hosts: nvme1 must not claim nvme10's IRQs,
        # virtio1 must not claim virtio10's
        dense = [
            " 30:  0  PCI-MSIX nvme1q0\n",
            " 31:  0  PCI-MSIX nvme10q0\n",
            " 32:  0  virtio1-requests\n",
            " 33:  0  virtio10-requests\n",
        ]
        assert _find_irqs(dense, _irq_candidates("nvme1n1")) == [30]
        assert _find_irqs(dense, _irq_candidates("vdb", "virtio1")) == [32]

    def test_irq_steering_with_explicit_node(self, tmp_path, monkeypatch):
        """irq_affinity must engage even when numa_node is set explicitly —
        the IRQs belong to the device, which still needs one lookup."""
        import strom.utils.numa as nmod

        p = str(tmp_path / "f.bin")
        with open(p, "wb") as f:
            f.write(b"a" * 4096)
        calls = []
        monkeypatch.setattr(nmod, "set_irq_affinity",
                            lambda name, node: calls.append((name, node)) or 1)
        na = NumaAffinity(node=0, steer_irqs=True)
        assert na.resolve(p) == 0
        na.resolve(p)  # steering runs once, not per call
        assert len(calls) == 1 and calls[0][1] == 0


class TestNumaAffinity:
    @pytest.mark.skipif(not _HAS_NODE0, reason="no sysfs NUMA topology")
    def test_explicit_node(self):
        before = os.sched_getaffinity(0)
        try:
            na = NumaAffinity(node=0)
            assert na.resolve(None) == 0
            assert na.ensure_thread()
            assert na.ensure_thread()  # idempotent per thread
            arr = alloc_aligned(4096)
            na.bind(arr)
        finally:
            os.sched_setaffinity(0, before)

    def test_auto_resolve_uma_is_noop(self, tmp_path):
        # on this box the backing device reports no NUMA node -> every call
        # degrades to a no-op instead of raising
        p = str(tmp_path / "f.bin")
        with open(p, "wb") as f:
            f.write(b"a" * 4096)
        na = NumaAffinity(node=-1)
        node = na.resolve(p)
        if node is None:
            assert not na.ensure_thread(p)
            assert not na.bind(alloc_aligned(4096))

    def test_delivery_integration(self, tmp_path):
        """numa_affinity=True must not change delivered bytes."""
        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        p = str(tmp_path / "g.bin")
        rng = np.random.default_rng(11)
        golden = rng.integers(0, 256, size=64 * 1024, dtype=np.uint8)
        with open(p, "wb") as f:
            f.write(golden.tobytes())
        before = os.sched_getaffinity(0)
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8, numa_affinity=True,
                                       numa_node=0 if _HAS_NODE0 else -1))
        try:
            out = ctx.pread(p, length=64 * 1024)
            np.testing.assert_array_equal(out, golden)
        finally:
            ctx.close()
            os.sched_setaffinity(0, before)
