"""Intra-transfer streaming: read/transfer overlap inside one memcpy_ssd2tpu
(VERDICT.md missing #1: round 1 read the whole slab, then dispatched
device_put — no overlap within a transfer). ≙ the reference consumer's
double-buffered DMA/compute recycle loop (SURVEY.md §3.5)."""

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext, split_segments
from strom.delivery.shard import Segment

MiB = 1024 * 1024


class TestSplitSegments:
    def test_single_segment_split(self):
        pieces = split_segments([Segment(0, 0, 10 * MiB)], 4 * MiB)
        assert [(b, n) for b, n, _ in pieces] == [
            (0, 4 * MiB), (4 * MiB, 4 * MiB), (8 * MiB, 2 * MiB)]
        for base, n, segs in pieces:
            assert sum(s.length for s in segs) == n
            assert segs[0].file_offset == base  # contiguous source here
            assert segs[0].dest_offset == 0     # dest rebased per piece

    def test_multi_segment_tiling(self):
        # 3 source segments tiling dest [0, 6MiB) out of order
        segs = [Segment(50 * MiB, 2 * MiB, 2 * MiB),
                Segment(10 * MiB, 0, 2 * MiB),
                Segment(30 * MiB, 4 * MiB, 2 * MiB)]
        pieces = split_segments(segs, 3 * MiB)
        assert [(b, n) for b, n, _ in pieces] == [(0, 3 * MiB), (3 * MiB, 3 * MiB)]
        # piece 0 covers dest [0,3MiB): all of seg@10M, first half of seg@50M
        p0 = pieces[0][2]
        assert p0 == [Segment(10 * MiB, 0, 2 * MiB),
                      Segment(50 * MiB, 2 * MiB, 1 * MiB)]
        # piece 1 covers dest [3,6MiB): second half of seg@50M, all of seg@30M
        p1 = pieces[1][2]
        assert p1 == [Segment(50 * MiB + 1 * MiB, 0, 1 * MiB),
                      Segment(30 * MiB, 1 * MiB, 2 * MiB)]

    def test_chunk_larger_than_total(self):
        pieces = split_segments([Segment(0, 0, MiB)], 16 * MiB)
        assert len(pieces) == 1 and pieces[0][1] == MiB


@pytest.fixture()
def big_file(tmp_path, rng):
    data = rng.integers(0, 256, size=6 * MiB + 4096, dtype=np.uint8)
    p = tmp_path / "big.bin"
    data.tofile(p)
    return str(p), data


class TestStreamedDelivery:
    def _cfg(self, engine_name):
        # tiny thresholds so the CI-sized file exercises the streamed path
        return StromConfig(engine=engine_name, queue_depth=8, num_buffers=16,
                           overlap_chunk_bytes=1 * MiB, overlap_min_bytes=2 * MiB)

    def test_streamed_integrity_single_device(self, engine_name, big_file):
        import jax

        path, golden = big_file
        ctx = StromContext(self._cfg(engine_name))
        try:
            arr = ctx.memcpy_ssd2tpu(path, length=6 * MiB,
                                     device=jax.devices()[0])
            np.testing.assert_array_equal(np.asarray(arr), golden[: 6 * MiB])
        finally:
            ctx.close()

    def test_streamed_integrity_with_shape_dtype(self, engine_name, big_file):
        path, golden = big_file
        ctx = StromContext(self._cfg(engine_name))
        try:
            arr = ctx.memcpy_ssd2tpu(path, shape=(3 * MiB // 4, 2),
                                     dtype=np.uint32)
            np.testing.assert_array_equal(
                np.asarray(arr),
                golden[: 6 * MiB].view(np.uint32).reshape(3 * MiB // 4, 2))
        finally:
            ctx.close()

    def test_streamed_sharded(self, engine_name, big_file):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from strom.parallel.mesh import make_mesh

        path, golden = big_file
        mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
        sharding = NamedSharding(mesh, P("dp"))
        ctx = StromContext(self._cfg(engine_name))
        try:
            arr = ctx.memcpy_ssd2tpu(path, shape=(6 * MiB,), dtype=np.uint8,
                                     sharding=sharding)
            np.testing.assert_array_equal(np.asarray(arr), golden[: 6 * MiB])
            # each device shard (3MiB) exceeded overlap_min -> streamed
            for s in arr.addressable_shards:
                assert s.data.shape == (3 * MiB,)
        finally:
            ctx.close()

    def test_streamed_offset_and_eof_error(self, engine_name, big_file):
        from strom.engine.base import EngineError

        path, golden = big_file
        ctx = StromContext(self._cfg(engine_name))
        try:
            arr = ctx.memcpy_ssd2tpu(path, offset=4096, length=4 * MiB)
            np.testing.assert_array_equal(np.asarray(arr),
                                          golden[4096: 4096 + 4 * MiB])
            with pytest.raises(EngineError):
                ctx.memcpy_ssd2tpu(path, offset=4 * MiB, length=4 * MiB)
        finally:
            ctx.close()

    def test_async_streamed(self, engine_name, big_file):
        path, golden = big_file
        ctx = StromContext(self._cfg(engine_name))
        try:
            h = ctx.memcpy_ssd2tpu(path, length=4 * MiB, async_=True)
            arr = h.block_until_ready()
            np.testing.assert_array_equal(np.asarray(arr), golden[: 4 * MiB])
        finally:
            ctx.close()
