"""Async snapshot-then-commit checkpointing (ISSUE 14 tentpole): commit
equivalence with the blocking save, snapshot isolation from in-place
mutation, the failure latch (old checkpoint intact, typed error on the
next save/wait, flight bundle dumped), chaos_writes never corrupting
last_committed, and the cross-process recovery helpers."""

import json
import os

import numpy as np
import pytest

from strom.ckpt import (AsyncCheckpointer, CkptAsyncError, CkptError,
                        clean_orphans, last_committed, restore_checkpoint,
                        save_checkpoint)
from strom.ckpt.jobstate import TOKEN_KEY, StepToken
from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.pipelines.sampler import SamplerState

jax = pytest.importorskip("jax")
jnp = jax.numpy


def _ctx(**kw):
    return StromContext(StromConfig(engine="python", queue_depth=8,
                                    num_buffers=16,
                                    slab_pool_bytes=64 * 1024 * 1024, **kw))


@pytest.fixture()
def ctx():
    c = _ctx()
    yield c
    c.close()


def _state(n=1 << 16):
    return {"w": jnp.arange(n, dtype=jnp.float32),
            "b": jnp.ones((257,), dtype=jnp.bfloat16),
            "step": np.array(3, dtype=np.int64)}


def _assert_tree_equal(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestAsyncSave:
    def test_commit_matches_blocking_save(self, ctx, tmp_path):
        """An async commit restores bit-exact, exactly like the blocking
        path (they share _commit_checkpoint), and wait() returns the
        manifest the blocking save would have."""
        state = _state()
        d = str(tmp_path / "ckpt")
        with AsyncCheckpointer(ctx, d) as cp:
            assert cp.last_committed() is None
            cp.save(state)
            m = cp.wait()
            assert cp.last_committed() == os.path.abspath(d)
        assert m["payload_bytes"] > 0
        back = restore_checkpoint(ctx, d, state, verify=True)
        _assert_tree_equal(state, back)

    def test_snapshot_isolated_from_mutation(self, ctx, tmp_path):
        """The snapshot half: a numpy leaf mutated IN PLACE right after
        save() returns must not leak into the committed bytes."""
        state = {"buf": np.arange(4096, dtype=np.int64)}
        want = state["buf"].copy()
        d = str(tmp_path / "ckpt")
        with AsyncCheckpointer(ctx, d) as cp:
            cp.save(state)
            state["buf"][:] = -1          # training mutates immediately
            cp.wait()
        back = restore_checkpoint(ctx, d, {"buf": want}, verify=True)
        np.testing.assert_array_equal(back["buf"], want)

    def test_token_rides_manifest_atomically(self, ctx, tmp_path):
        tok = StepToken(sampler=SamplerState(epoch=2, batch_in_epoch=5,
                                             seed=7), consumed=21)
        d = str(tmp_path / "ckpt")
        with AsyncCheckpointer(ctx, d) as cp:
            cp.save(_state(1 << 10), extra={TOKEN_KEY: tok.to_dict()})
            cp.wait()
        lc = last_committed(d)
        assert lc is not None
        got = StepToken.from_manifest(lc[1])
        assert got.consumed == 21 and got.sampler.epoch == 2

    def test_backpressure_one_in_flight(self, ctx, tmp_path):
        """A second save drains the first commit before snapshotting —
        never two commits racing one tmp dir."""
        d = str(tmp_path / "ckpt")
        state = _state(1 << 18)
        with AsyncCheckpointer(ctx, d) as cp:
            cp.save(state)
            cp.save(state)     # must not raise / race
            assert cp.wait()["payload_bytes"] > 0
            assert cp.commits == 2


class TestFailureLatch:
    def _failing_plan(self, skip_ops: int) -> str:
        # every write op past the window start fails with EIO at p=1:
        # retries exhaust the budget, the commit fails deterministically
        return json.dumps({"seed": 0, "rules": [
            {"kind": "errno", "op": "write", "op_lo": skip_ops,
             "err": "EIO"}]})

    def test_failed_commit_keeps_old_checkpoint_and_raises_on_wait(
            self, tmp_path):
        d = str(tmp_path / "ckpt")
        state = _state(1 << 14)
        ctx0 = _ctx()
        try:
            save_checkpoint(ctx0, d, state,
                            extra={TOKEN_KEY: StepToken(
                                sampler=SamplerState(seed=1),
                                consumed=4).to_dict()})
        finally:
            ctx0.close()
        ctx = _ctx(fault_plan=self._failing_plan(0), io_retries=1)
        try:
            cp = AsyncCheckpointer(ctx, d)
            cp.save(state)
            with pytest.raises(CkptAsyncError) as ei:
                cp.wait()
            assert "previous checkpoint is intact" in str(ei.value)
            # the latch cleared on raise; the failure never touched the
            # committed checkpoint — resume falls back to the prior commit
            lc = last_committed(d)
            assert lc is not None
            assert StepToken.from_manifest(lc[1]).consumed == 4
            cp.close(wait=False)
        finally:
            ctx.close()
        # the failed save's tmp orphan is sweepable, the commit loadable
        clean_orphans(d)
        ctx2 = _ctx()
        try:
            back = restore_checkpoint(ctx2, d, state, verify=True)
            _assert_tree_equal(state, back)
        finally:
            ctx2.close()

    def test_failed_commit_raises_on_next_save(self, tmp_path):
        ctx = _ctx(fault_plan=self._failing_plan(0), io_retries=1)
        try:
            cp = AsyncCheckpointer(ctx, str(tmp_path / "ckpt"))
            state = _state(1 << 12)
            cp.save(state)
            with pytest.raises(CkptAsyncError):
                cp.save(state)      # the latch fires here, not silently
            cp.close(wait=False)
        finally:
            ctx.close()

    def test_failed_commit_dumps_flight_bundle(self, tmp_path):
        fdir = str(tmp_path / "flight")
        ctx = _ctx(fault_plan=self._failing_plan(0), io_retries=1,
                   flight_dir=fdir, flight_stall_s=0.0)
        try:
            cp = AsyncCheckpointer(ctx, str(tmp_path / "ckpt"))
            cp.save(_state(1 << 12))
            with pytest.raises(CkptAsyncError):
                cp.wait()
            cp.close(wait=False)
            bundles = [b for b in os.listdir(fdir)
                       if "ckpt_commit_failed" in b]
            assert bundles, f"no ckpt_commit_failed bundle in {fdir}"
            from strom.obs.flight import load_bundle

            doc = load_bundle(os.path.join(fdir, bundles[0]))
            assert doc["manifest"]["reason"] == "ckpt_commit_failed"
        finally:
            ctx.close()


class TestChaosWrites:
    def test_chaos_writes_never_corrupt_last_committed(self, tmp_path):
        """ISSUE 14 satellite: transient write chaos (EIO + short writes)
        during async commits is absorbed by the write retry machinery —
        every commit that REPORTS success restores CRC-verified bit-exact,
        and a restart between any two saves finds a valid checkpoint."""
        ctx = _ctx(fault_plan="chaos_writes:11", io_retries=3)
        d = str(tmp_path / "ckpt")
        try:
            with AsyncCheckpointer(ctx, d) as cp:
                for i in range(4):
                    # big enough that the plan's p=0.02 rules fire over
                    # the ~32 write ops each save submits
                    state = {"w": jnp.full((1 << 20,), float(i),
                                           dtype=jnp.float32),
                             "i": np.array(i)}
                    cp.save(state, extra={"i": i})
                    m = cp.wait()   # commit i reported durable
                    assert m["extra"]["i"] == i
                    lc = last_committed(d)
                    assert lc is not None
                    back = restore_checkpoint(ctx, d, state, verify=True)
                    np.testing.assert_array_equal(
                        np.asarray(back["w"]), np.asarray(state["w"]))
            plan = ctx.engine.plan.stats()
            assert plan["faults_injected"] > 0, \
                "chaos_writes plan never fired — the test proved nothing"
        finally:
            ctx.close()


class TestRecoveryHelpers:
    def test_last_committed_rolls_back_between_renames_hole(self, ctx,
                                                            tmp_path):
        """A kill exactly between the replace-commit's two renames leaves
        only <dir>.old-<pid>; last_committed restores it."""
        d = str(tmp_path / "ckpt")
        state = _state(1 << 10)
        save_checkpoint(ctx, d, state)
        os.rename(d, f"{d}.old-99999")    # simulate the hole
        lc = last_committed(d)
        assert lc is not None and lc[0] == os.path.abspath(d)
        back = restore_checkpoint(ctx, d, state, verify=True)
        _assert_tree_equal(state, back)

    def test_clean_orphans_sweeps_tmp_never_the_commit(self, ctx, tmp_path):
        d = str(tmp_path / "ckpt")
        save_checkpoint(ctx, d, _state(1 << 10))
        os.makedirs(f"{d}.tmp-12345")
        os.makedirs(f"{d}.old-12345")
        removed = clean_orphans(d)
        assert len(removed) == 2
        assert last_committed(d) is not None

    def test_last_committed_none_when_nothing(self, tmp_path):
        assert last_committed(str(tmp_path / "nope")) is None
        with pytest.raises(CkptError):
            restore_checkpoint(None, str(tmp_path / "nope"), {})
