"""The examples/ scripts are user-facing documentation — they must stay
runnable. Each runs as a real subprocess on the CPU backend (--cpu: the
scripts pin the backend via jax.config before first touch, because this
sandbox re-pins JAX_PLATFORMS at interpreter startup)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


@pytest.mark.parametrize("script,expect", [
    ("ssd_to_tpu.py", "integrity: delivered bytes == file bytes"),
    ("train_llama_tiny.py", "step 4:"),
    ("parquet_scan.py", "dot(value, weight):"),
])
def test_example_runs(script, expect):
    res = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script), "--cpu"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert res.returncode == 0, res.stderr[-2000:]
    assert expect in res.stdout, res.stdout
