"""tools/compare_rounds.py — the judge-facing round comparison table. It
reads driver-recorded BENCH_r*.json artifacts of THREE vintages (raw bench
line, driver-wrapped {'parsed': ...}, tail-scrape fallback) and must keep
rendering all of them as the artifact schema grows."""

import importlib.util
import json
import os
import sys

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "compare_rounds",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools", "compare_rounds.py"))
compare_rounds = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(compare_rounds)


OLD_ROUND = {  # r2-era shape: no binding object, top-level fields only
    "metric": "ssd2hbm_bandwidth", "value": 0.0076, "unit": "GB/s",
    "vs_link": 0.9901, "link_busy_frac": 0.9933, "train_data_stalls": 1,
    "raw_gbps": 3.0055,
}
NEW_ROUND = {  # r5-era shape: binding + context + audit arrays + headline
    "metric": "ssd2hbm_bandwidth", "value": 0.019, "unit": "GB/s",
    "raw_gbps": 3.49,
    "raw_gbps_passes": [0.78, 3.14, 3.49, 2.96],
    "train_data_stalls_attempts": [0],
    "bounded_vision_headline": {"shape": "64x224", "attempted": False,
                                "link_probe_gbps": 0.0175, "stalls": None},
    # r6+: decode-path counters from the JPEG vision arms
    "resnet_images_per_s": 271.5,
    "resnet_decode_reduced_hits_2": 640,
    "resnet_decode_slot_bytes": 123456789,
    # r6+: per-step stall attribution (strom/obs/stall)
    "resnet_goodput_pct": 83.4,
    "resnet_step_ingest_wait_p50_us": 151000.0,
    # r6+: hot-set cache cold/warm epoch pair (strom/delivery/hotcache)
    "resnet_predecoded_warm_vs_cold": 2.208,
    "resnet_predecoded_cache_hit_bytes": 4411304,
    "resnet_predecoded_cache_miss_bytes": 0,
    # r6+: intra-batch streaming (strom/delivery/stream) + the --no-stream
    # A/B arm's companion columns
    "resnet_stream_intra_batch": True,
    "resnet_stream_batches": 14,
    "resnet_stream_samples_early": 301,
    "resnet_nostream_data_stalls": 6,
    # r12+: decode path v2 (native/fused/ROI A/B + decoded-output cache)
    "resnet_decode_native_img_per_s": 661.0,
    "resnet_decode_native_vs_cv2": 2.054,
    "resnet_decode_roi_rows_skipped": 31744,
    "resnet_decode_cache_warm_vs_cold": 3.117,
    # r7+: multi-tenant scheduler arm (strom/sched)
    "mt_vs_solo_mean": 0.913,
    "mt_pq_sched_queue_wait_p99_us": 65536.0,
    "mt_pq_items_per_s": 134358.2,
    "mt_vis0_vs_solo": 0.971,
    # r9+: seeded-chaos resilience arm (strom/faults + strom/engine/
    # resilience): bit-identical-under-faults bit + bounded slowdown
    "chaos_ok": 1,
    "chaos_slowdown": 1.173,
    "chaos_faults_injected": 37,
    "chaos_chunk_retries": 29,
    "binding": {"vs_baseline_host": 1.0315, "vs_baseline_host_raid": 0.9708,
                "train_data_stalls": 0, "some_future_key": 0.5},
    "context": {"raw_gbps": 3.49},
}
DRIVER_WRAPPED = {  # how the driver records it: cmd/rc/tail + parsed
    "n": 4, "cmd": "python bench.py", "rc": 0,
    "tail": "device: TPU\n" + json.dumps(OLD_ROUND) + "\n",
    "parsed": OLD_ROUND,
}


@pytest.fixture()
def artifacts(tmp_path):
    paths = []
    for name, doc in (("BENCH_r02.json", DRIVER_WRAPPED),
                      ("BENCH_r05.json", NEW_ROUND)):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        paths.append(str(p))
    return paths


def test_table_renders_all_vintages(artifacts, capsys):
    assert compare_rounds.main(artifacts) == 0
    out = capsys.readouterr().out
    # binding rows: known keys plus self-described ones the tool predates
    assert "vs_baseline_host" in out
    assert "some_future_key" in out
    # old round resolved through the driver wrapper's parsed dict
    assert "0.9901" in out
    # audit arrays render compactly (no raw list repr blowing the column)
    assert "0.78..3.49x4" in out
    assert "[0.78" not in out
    # the headline gating decision is visible as a decision, not a blank
    assert "skip@0.0175" in out
    # decode-path section: JPEG-arm img/s + the engaged-optimization
    # counters render for rounds that carry them, "-" for older rounds
    assert "decode path" in out
    assert "resnet_decode_reduced_hits_2" in out
    assert "640" in out
    # stall-attribution section (ISSUE 3): goodput + bucket medians render
    assert "stall attribution" in out
    assert "resnet_goodput_pct" in out
    assert "83.4" in out
    # hot-set cache section (ISSUE 4): warm/cold ratio + hit/miss bytes
    assert "hot-set cache" in out
    assert "resnet_predecoded_warm_vs_cold" in out
    assert "2.208" in out


def test_cache_section_hidden_without_cache_keys(tmp_path, capsys):
    """Rounds predating the hot cache don't get an all-dash cache section."""
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "hot-set cache" not in capsys.readouterr().out


def test_cache_keys_match_producers():
    """Producer↔report key parity (ISSUE 4 satellite, the decode/stall
    pattern): every compare_rounds cache column must be an arm prefix plus
    a key cli._cache_epoch_phases actually emits (single-sourced in
    strom.delivery.hotcache.CACHE_BENCH_FIELDS) — a rename on either side
    fails HERE, not on a dashboard."""
    from strom.delivery.hotcache import CACHE_BENCH_FIELDS

    prefixes = ("resnet_predecoded", "vit_predecoded", "resnet", "vit")
    produced = set(CACHE_BENCH_FIELDS)
    for key in compare_rounds.CACHE_KEYS:
        suffix = next((key[len(p) + 1:] for p in prefixes
                       if key.startswith(p + "_")), None)
        assert suffix is not None, key
        assert suffix in produced, \
            f"compare_rounds consumes {key!r} but the cache phase pair " \
            f"produces no {suffix!r} (renamed column?)"


def test_stream_section_renders(artifacts, capsys):
    """r6+ artifacts get the streaming section with the A/B rows."""
    assert compare_rounds.main(artifacts) == 0
    out = capsys.readouterr().out
    assert "streaming" in out
    assert "resnet_stream_samples_early" in out
    assert "resnet_nostream_data_stalls" in out


def test_stream_section_hidden_without_stream_keys(tmp_path, capsys):
    """Rounds predating intra-batch streaming don't get an all-dash
    streaming section."""
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "streaming" not in capsys.readouterr().out


def test_stream_keys_match_producers():
    """Producer↔report key parity for the streaming section (ISSUE 5
    satellite, the decode/stall/cache pattern): every *_stream_* column
    must be an arm prefix plus a key the bench arms actually emit
    (single-sourced in strom.delivery.stream.STREAM_FIELDS plus the
    stream_intra_batch flag); the resnet_nostream_* A/B rows must be
    ordinary arm columns (img/s, stalls, stall attribution)."""
    from strom.delivery.stream import STREAM_FIELDS
    from strom.obs.stall import STALL_FIELDS

    prefixes = ("resnet_nostream", "resnet", "vit")
    stream_produced = set(STREAM_FIELDS) | {"stream_intra_batch"}
    arm_produced = set(STALL_FIELDS) | {
        "images_per_s", "train_images_per_s", "data_stalls"}
    for key in compare_rounds.STREAM_KEYS:
        prefix = next((p for p in prefixes if key.startswith(p + "_")), None)
        assert prefix is not None, key
        suffix = key[len(prefix) + 1:]
        produced = stream_produced if suffix.startswith("stream") \
            else arm_produced
        assert suffix in produced, \
            f"compare_rounds consumes {key!r} but the bench arms produce " \
            f"no {suffix!r} (renamed column?)"


def test_decode2_section_renders(artifacts, capsys):
    """r12+ artifacts get the decode-v2 section with the native-vs-cv2
    ratio and the decoded-cache warm/cold row."""
    assert compare_rounds.main(artifacts) == 0
    out = capsys.readouterr().out
    assert "decode v2" in out
    assert "resnet_decode_native_vs_cv2" in out
    assert "2.054" in out
    assert "resnet_decode_cache_warm_vs_cold" in out
    assert "3.117" in out


def test_decode2_section_hidden_without_keys(tmp_path, capsys):
    """Rounds predating decode v2 don't get an all-dash section."""
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "decode v2" not in capsys.readouterr().out


def test_decode2_keys_match_producers():
    """Producer↔report key parity for the decode-v2 section (ISSUE 12
    satellite, the decode/stall/cache/stream/sched/slo pattern): every
    compare_rounds decode-v2 column must be an arm prefix plus a key
    cli._decode2_phases actually emits (single-sourced in
    strom.formats.jpeg.DECODE2_FIELDS) — a rename on either side fails
    HERE, not on a dashboard."""
    from strom.formats.jpeg import DECODE2_FIELDS

    prefixes = ("resnet", "vit")
    produced = set(DECODE2_FIELDS)
    for key in compare_rounds.DECODE2_KEYS:
        prefix = next((p for p in prefixes if key.startswith(p + "_")), None)
        assert prefix is not None, key
        suffix = key[len(prefix) + 1:]
        assert suffix in produced, \
            f"compare_rounds consumes {key!r} but the decode-v2 phases " \
            f"produce no {suffix!r} (renamed column?)"


def test_slo_keys_match_producers():
    """Producer↔report key parity for the request-latency / SLO section
    (ISSUE 8, the decode/stall/cache/stream/sched pattern): every
    <arm>_req_lat_* / <arm>_slo_ok column must be an arm prefix plus a
    key the vision bench arms actually emit (single-sourced in
    strom.obs.slo.SLO_BENCH_FIELDS)."""
    from strom.obs.slo import SLO_BENCH_FIELDS

    prefixes = ("resnet", "vit")
    produced = set(SLO_BENCH_FIELDS)
    for key in compare_rounds.SLO_KEYS:
        prefix = next((p for p in prefixes if key.startswith(p + "_")), None)
        assert prefix is not None, key
        suffix = key[len(prefix) + 1:]
        assert suffix in produced, \
            f"compare_rounds consumes {key!r} but the bench arms produce " \
            f"no {suffix!r} (renamed column?)"


def test_resil_keys_match_producers():
    """Producer↔report key parity for the resilience section (ISSUE 9
    satellite, the decode/stall/cache/stream/sched/slo pattern): the
    compare_rounds chaos columns must be EXACTLY the keys the chaos bench
    arm emits (single-sourced in
    strom.engine.resilience.CHAOS_BENCH_FIELDS) — a rename on either side
    is a silently dead column."""
    from strom.engine.resilience import CHAOS_BENCH_FIELDS

    assert list(compare_rounds.RESIL_KEYS) == list(CHAOS_BENCH_FIELDS)


def test_resil_section_renders(artifacts, capsys):
    """r9+ artifacts get the resilience section with the bit-identical
    chaos bit and the absorbed-fault counters."""
    assert compare_rounds.main(artifacts) == 0
    out = capsys.readouterr().out
    assert "resilience" in out
    assert "chaos_ok" in out
    assert "chaos_slowdown" in out
    assert "1.173" in out


def test_resil_section_hidden_without_chaos_keys(tmp_path, capsys):
    """Rounds predating the chaos arm don't get an all-dash section."""
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "resilience" not in capsys.readouterr().out


def test_sched_section_renders(artifacts, capsys):
    """r7+ artifacts get the multi-tenant section with the no-starvation
    row (light tenant queue-wait p99)."""
    assert compare_rounds.main(artifacts) == 0
    out = capsys.readouterr().out
    assert "multi-tenant" in out
    assert "mt_vs_solo_mean" in out
    assert "mt_pq_sched_queue_wait_p99_us" in out
    assert "0.913" in out


def test_sched_section_hidden_without_sched_keys(tmp_path, capsys):
    """Rounds predating the scheduler don't get an all-dash section."""
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "multi-tenant" not in capsys.readouterr().out


def test_sched_keys_match_producers():
    """Producer↔report key parity for the multi-tenant section (ISSUE 7
    satellite, the decode/stall/cache/stream pattern): every mt_<tenant>_*
    column must be a tenant prefix plus a suffix the multitenant bench arm
    actually emits (single-sourced in strom.sched.scheduler.SCHED_FIELDS,
    plus the solo baseline column); mt_vs_solo_mean is the one aggregate
    column."""
    from strom.sched.scheduler import SCHED_FIELDS

    prefixes = ("mt_vis0", "mt_vis1", "mt_pq")
    produced = set(SCHED_FIELDS) | {"solo_items_per_s"}
    for key in compare_rounds.SCHED_KEYS:
        if key == "mt_vs_solo_mean":
            continue
        prefix = next((p for p in prefixes if key.startswith(p + "_")), None)
        assert prefix is not None, key
        suffix = key[len(prefix) + 1:]
        assert suffix in produced, \
            f"compare_rounds consumes {key!r} but the multitenant arm " \
            f"produces no {suffix!r} (renamed column?)"


def test_stall_section_hidden_without_stall_keys(tmp_path, capsys):
    """Rounds predating stall attribution don't get an all-dash section."""
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "stall attribution" not in capsys.readouterr().out


def test_decode_section_hidden_without_decode_keys(tmp_path, capsys):
    """Rounds that predate the decode counters don't get an all-dash decode
    section tacked onto the table."""
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "decode path" not in capsys.readouterr().out


def test_tail_scrape_fallback(tmp_path, capsys):
    """A wrapper with no usable 'parsed' falls back to scraping the JSON
    line out of 'tail'."""
    doc = {"cmd": "python bench.py", "rc": 0,
           "tail": "noise\n" + json.dumps(OLD_ROUND) + "\n"}
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps(doc))
    assert compare_rounds.main([str(p)]) == 0
    assert "0.9901" in capsys.readouterr().out


def test_unreadable_artifact_flagged_invalid(tmp_path, capsys):
    """A corrupt artifact keeps its column with a visible INVALID status
    (ISSUE 6 satellite) instead of being silently dropped."""
    good = tmp_path / "BENCH_r05.json"
    good.write_text(json.dumps(NEW_ROUND))
    bad = tmp_path / "BENCH_r04.json"
    bad.write_text("{not json")
    assert compare_rounds.main([str(bad), str(good)]) == 0
    captured = capsys.readouterr()
    assert "invalid round" in captured.err
    assert "INVALID(unreadable)" in captured.out
    assert "vs_baseline_host" in captured.out


def test_rc124_round_flagged_invalid(tmp_path, capsys):
    """BENCH_r05's shape today: rc=124, parsed=null — a visible invalid
    column, no crash, the good rounds still tabulate."""
    good = tmp_path / "BENCH_r01.json"
    good.write_text(json.dumps(NEW_ROUND))
    dead = tmp_path / "BENCH_r02.json"
    dead.write_text(json.dumps(
        {"n": 5, "cmd": "python bench.py", "rc": 124, "tail": None,
         "parsed": None}))
    assert compare_rounds.main([str(good), str(dead)]) == 0
    captured = capsys.readouterr()
    assert "INVALID(rc=124" in captured.out
    assert "vs_baseline_host" in captured.out


def test_no_artifacts_errors(tmp_path, capsys):
    assert compare_rounds.main([str(tmp_path / "missing.json")]) == 1


def test_write_keys_match_producers():
    """Producer↔report key parity for the write-path section (ISSUE 13
    satellite, the decode/stall/cache/stream/sched/slo/resil pattern):
    every compare_rounds write column must be a key the checkpoint bench
    arm emits (single-sourced in strom.ckpt.checkpoint.CKPT_FIELDS and
    strom.delivery.spill.SPILL_FIELDS) — a rename on either side is a
    silently dead column."""
    from strom.ckpt.checkpoint import CKPT_FIELDS
    from strom.delivery.spill import SPILL_FIELDS

    produced = set(CKPT_FIELDS) | set(SPILL_FIELDS) | {"ckpt_bytes"}
    for key in compare_rounds.WRITE_KEYS:
        assert key in produced, \
            f"compare_rounds consumes {key!r} but the checkpoint arm " \
            f"produces no such key (renamed column?)"


def test_resume_keys_match_producers():
    """Producer↔report key parity for the preemption/resume section
    (ISSUE 14 tentpole, the decode/stall/cache/stream/sched/slo/resil/
    write pattern): every compare_rounds resume column must be a key the
    resume bench arm emits (single-sourced in
    strom.ckpt.jobstate.RESUME_FIELDS and
    strom.ckpt.async_save.CKPT_ASYNC_FIELDS) — a rename on either side is
    a silently dead column."""
    from strom.ckpt.async_save import CKPT_ASYNC_FIELDS
    from strom.ckpt.jobstate import RESUME_FIELDS

    produced = set(RESUME_FIELDS) | set(CKPT_ASYNC_FIELDS)
    for key in compare_rounds.RESUME_KEYS:
        assert key in produced, \
            f"compare_rounds consumes {key!r} but the resume arm " \
            f"produces no such key (renamed column?)"


def test_dist_keys_match_producers():
    """Producer↔report key parity for the distributed section (ISSUE 15
    tentpole, the decode/stall/cache/stream/sched/slo/resil/write/resume
    pattern): every compare_rounds dist column must be a key the dist
    bench arm emits (single-sourced in
    strom.dist.peers.DIST_BENCH_FIELDS) — a rename on either side is a
    silently dead column."""
    from strom.dist.peers import DIST_BENCH_FIELDS

    produced = set(DIST_BENCH_FIELDS)
    for key in compare_rounds.DIST_KEYS:
        assert key in produced, \
            f"compare_rounds consumes {key!r} but the dist arm " \
            f"produces no such key (renamed column?)"


def test_cluster_keys_match_producers():
    """Producer↔report key parity for the cluster-obs section (ISSUE 18,
    same contract as the other sections): every compare_rounds cluster
    column must be a key the federation emits (single-sourced in
    strom.obs.federation.FED_FIELDS) — a rename on either side is a
    silently dead column."""
    from strom.obs.federation import FED_FIELDS

    produced = set(FED_FIELDS)
    for key in compare_rounds.CLUSTER_KEYS:
        assert key in produced, \
            f"compare_rounds consumes {key!r} but the federation " \
            f"produces no such key (renamed column?)"
    # and the other direction: every FED gauge the bench copies renders
    assert produced == set(compare_rounds.CLUSTER_KEYS)


def test_cluster_section_renders(tmp_path, capsys):
    """A round carrying cluster_* keys gets the cluster obs section."""
    d = dict(NEW_ROUND)
    d.update({"cluster_hosts": 2, "cluster_hosts_unhealthy": 0,
              "cluster_trace_linked_ratio": 1.0,
              "cluster_scrape_lag_p99_us": 2048.0})
    p = tmp_path / "BENCH_r18.json"
    p.write_text(json.dumps(d))
    assert compare_rounds.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "cluster obs (rank-0 federation" in out
    assert "cluster_hosts_unhealthy" in out


def test_cluster_section_hidden_without_cluster_keys(tmp_path, capsys):
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps(dict(NEW_ROUND)))
    assert compare_rounds.main([str(p)]) == 0
    assert "cluster obs (rank-0" not in capsys.readouterr().out


def test_dist_section_renders(tmp_path, capsys):
    """A round carrying dist_* keys gets the distributed section."""
    d = dict(NEW_ROUND)
    d.update({"dist_ok": 1, "dist_procs": 2, "dist_items_per_s": 1502.3,
              "dist_peer_hit_ratio": 0.53, "dist_engine_ingest_bytes": 0})
    p = tmp_path / "BENCH_r15.json"
    p.write_text(json.dumps(d))
    assert compare_rounds.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "distributed (N-process data plane" in out
    assert "dist_peer_hit_ratio" in out


def test_dist_section_hidden_without_dist_keys(tmp_path, capsys):
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps(dict(NEW_ROUND)))
    assert compare_rounds.main([str(p)]) == 0
    assert "distributed (N-process" not in capsys.readouterr().out


def test_resume_section_renders(tmp_path, capsys):
    """A round carrying resume_*/ckpt_async_* keys gets the resume
    section."""
    d = dict(NEW_ROUND)
    d.update({"resume_ok": 1, "resume_kill_step": 12,
              "resume_restart_step": 8, "resume_replayed_batches": 5,
              "ckpt_async_stall_frac": 0.021,
              "ckpt_async_stall_p99_us": 1481.4})
    p = tmp_path / "BENCH_r14.json"
    p.write_text(json.dumps(d))
    assert compare_rounds.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "resume (kill/restart harness" in out
    assert "resume_ok" in out and "ckpt_async_stall_frac" in out


def test_write_section_renders(tmp_path, capsys):
    """A round carrying ckpt_*/spill_* keys gets the write-path section."""
    d = dict(NEW_ROUND)
    d.update({"ckpt_save_mb_per_s": 409.1, "ckpt_save_vs_pickle": 1.154,
              "ckpt_roundtrip_ok": 1, "spill_hit_bytes": 16777216,
              "spill_cache_miss_bytes": 0, "spill_hit_ratio": 0.5})
    p = tmp_path / "BENCH_r13.json"
    p.write_text(json.dumps(d))
    assert compare_rounds.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "write path" in out
    assert "ckpt_save_vs_pickle" in out
    assert "spill_cache_miss_bytes" in out


def test_write_section_hidden_without_keys(tmp_path, capsys):
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "write path" not in capsys.readouterr().out


def test_tune_keys_match_producers():
    """Producer↔report key parity for the kernel-bypass/autotune section
    (ISSUE 16, the decode/stall/.../dist pattern): the compare_rounds
    tune columns must be EXACTLY the keys the tune + nvme bench arms emit
    (single-sourced in strom.tune.TUNE_BENCH_FIELDS) — a rename on either
    side is a silently dead column."""
    from strom.tune import TUNE_BENCH_FIELDS

    assert list(compare_rounds.TUNE_KEYS) == list(TUNE_BENCH_FIELDS)


def test_tune_section_renders(tmp_path, capsys):
    """A round carrying tune/sqpoll keys gets the kernel-bypass section."""
    d = dict(NEW_ROUND)
    d.update({"hand_items_per_s": 2571.0, "tuned_items_per_s": 2728.4,
              "tuned_vs_hand": 1.0612, "tune_moves": 2, "tune_reverts": 1,
              "plain_submit_syscalls_per_gb": 238.4,
              "sqpoll_submit_syscalls_per_gb": 14.9, "sqpoll_active": 1})
    p = tmp_path / "BENCH_r16.json"
    p.write_text(json.dumps(d))
    assert compare_rounds.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "kernel bypass & autotune" in out
    assert "tuned_vs_hand" in out
    assert "sqpoll_submit_syscalls_per_gb" in out
    assert "1.061" in out


def test_tune_section_hidden_without_tune_keys(tmp_path, capsys):
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "kernel bypass" not in capsys.readouterr().out


def test_pushdown_keys_match_producers():
    """Producer↔report key parity for the near-data pushdown section
    (ISSUE 19, the decode/stall/.../tune pattern): the compare_rounds
    pushdown columns must be EXACTLY the keys the parquet pushdown A/B
    and the dist arm's compressed-wire pass emit (single-sourced in
    strom.ops.pushdown.PUSHDOWN_BENCH_FIELDS) — a rename on either side
    is a silently dead column."""
    from strom.ops.pushdown import PUSHDOWN_BENCH_FIELDS

    assert list(compare_rounds.PUSHDOWN_KEYS) == list(PUSHDOWN_BENCH_FIELDS)


def test_pushdown_section_renders(tmp_path, capsys):
    """A round carrying pushdown/comp-wire keys gets the pushdown
    section."""
    d = dict(NEW_ROUND)
    d.update({"pushdown_ok": 1, "parquet_pushdown_rows_per_s": 5023174.2,
              "parquet_unpushed_rows_per_s": 3881202.9,
              "parquet_pushdown_vs_unpushed": 1.2943,
              "parquet_pushdown_skipped_bytes": 6291456,
              "parquet_pushdown_groups_skipped": 24,
              "parquet_pushdown_groups_total": 32,
              "dist_peer_raw_wire_bytes": 1048576,
              "dist_peer_comp_wire_bytes": 81920,
              "dist_peer_comp_vs_raw": 12.8, "peer_comp_ratio": 13.0})
    p = tmp_path / "BENCH_r19.json"
    p.write_text(json.dumps(d))
    assert compare_rounds.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "near-data pushdown" in out
    assert "parquet_pushdown_vs_unpushed" in out
    assert "dist_peer_comp_vs_raw" in out
    assert "12.8" in out


def test_pushdown_section_hidden_without_keys(tmp_path, capsys):
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "near-data pushdown" not in capsys.readouterr().out


def test_fabric_keys_match_producers():
    """Producer↔report key parity for the peer fabric v2 section (ISSUE
    20, the decode/stall/.../pushdown pattern): every compare_rounds
    fabric column must be a key the dist arm emits (single-sourced in
    strom.dist.peers.DIST_BENCH_FIELDS) — a rename on either side is a
    silently dead column."""
    from strom.dist.peers import DIST_BENCH_FIELDS

    produced = set(DIST_BENCH_FIELDS)
    for key in compare_rounds.FABRIC_KEYS:
        assert key in produced, \
            f"compare_rounds consumes {key!r} but the dist arm " \
            f"produces no such key (renamed column?)"


def test_fabric_section_renders(tmp_path, capsys):
    """A round carrying the batched-transport A/B keys gets the peer
    fabric v2 section."""
    d = dict(NEW_ROUND)
    d.update({"dist_batch_vs_single": 1.42,
              "dist_unbatched_items_per_s": 911.5,
              "peer_rtt_per_extent_us": 183.2,
              "peer_frame_hit_bytes": 602112,
              "peer_conn_reuse_ratio": 0.9167})
    p = tmp_path / "BENCH_r20.json"
    p.write_text(json.dumps(d))
    assert compare_rounds.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "peer fabric v2" in out
    assert "dist_batch_vs_single" in out
    assert "peer_conn_reuse_ratio" in out
    assert "1.42" in out


def test_fabric_section_hidden_without_keys(tmp_path, capsys):
    p = tmp_path / "BENCH_r03.json"
    p.write_text(json.dumps(OLD_ROUND))
    assert compare_rounds.main([str(p)]) == 0
    assert "peer fabric v2" not in capsys.readouterr().out
