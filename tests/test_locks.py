"""The runtime half of the lock discipline (ISSUE 11): WitnessLock's
process-wide acquisition graph raises a typed LockOrderError naming both
sites the moment two locks are ever taken in both orders, dumps a
loadable flight bundle at the cycle, and keeps a truthful held-stack
across Condition.wait. The static half is tests/test_stromlint.py."""

import threading

import pytest

from strom.obs.flight import load_bundle
from strom.utils import locks


@pytest.fixture
def witness_on():
    prev_enabled = locks.witness_enabled()
    locks.witness.reset()
    locks.enable_witness(True)
    try:
        yield
    finally:
        locks.enable_witness(prev_enabled)
        locks.witness.reset()
        locks.set_flight_dir(None)


def _seed_inversion(a, b):
    """Take a→b, then attempt b→a; returns the raised LockOrderError."""
    with a:
        with b:
            pass
    with pytest.raises(locks.LockOrderError) as ei:
        with b:
            with a:
                pass
    return ei.value


def test_inversion_raises_typed_error(witness_on):
    a = locks.WitnessLock("t.a")
    b = locks.WitnessLock("t.b")
    err = _seed_inversion(a, b)
    assert err.edge == ("t.b", "t.a")
    # both directions of the cycle carry their first-observed sites
    assert set(err.sites) == {"t.a -> t.b", "t.b -> t.a"}
    assert all("test_locks.py" in site for site in err.sites.values())


def test_three_lock_cycle_detected(witness_on):
    """A cycle through 3+ locks (A→B, B→C, then C→A) deadlocks just as
    surely as a direct inversion; the witness checks REACHABILITY, not
    just the direct reverse edge, and names every edge of the cycle."""
    a = locks.WitnessLock("t3.a")
    b = locks.WitnessLock("t3.b")
    c = locks.WitnessLock("t3.c")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(locks.LockOrderError) as ei:
        with c:
            with a:
                pass
    err = ei.value
    assert err.edge == ("t3.c", "t3.a")
    assert set(err.sites) == {"t3.c -> t3.a", "t3.a -> t3.b",
                              "t3.b -> t3.c"}
    assert "3-lock cycle" in str(err)


def test_witness_enable_reverts_on_ctx_close(witness_on):
    """StromContext(debug_locks=True) must not leave the process-global
    witness on for every later context (close() reverts exactly what
    __init__ enabled)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from strom.config import StromConfig
    from strom.delivery.core import StromContext

    locks.enable_witness(False)
    ctx = StromContext(StromConfig(debug_locks=True, sched_enabled=False,
                                   slab_pool_bytes=0))
    try:
        assert locks.witness_enabled()
    finally:
        ctx.close()
    assert not locks.witness_enabled()


def test_cycle_check_fires_before_acquiring(witness_on):
    """The raise happens BEFORE the inner lock is taken: the offending
    lock must remain free (a held leak here would convert every caught
    inversion into a later deadlock)."""
    a = locks.WitnessLock("t.a")
    b = locks.WitnessLock("t.b")
    _seed_inversion(a, b)
    assert not a.locked()
    assert not b.locked()


def test_same_name_never_self_cycles(witness_on):
    """Two instances of one ROLE (every _Counter shares 'stats.series')
    may nest without tripping the witness — the graph is keyed by role."""
    a1 = locks.WitnessLock("t.same")
    a2 = locks.WitnessLock("t.same")
    with a1:
        with a2:
            pass
    with a2:
        with a1:
            pass  # opposite instance order, same role: fine


def test_cycle_dumps_loadable_flight_bundle(witness_on, tmp_path):
    locks.set_flight_dir(str(tmp_path))
    a = locks.WitnessLock("t.a")
    b = locks.WitnessLock("t.b")
    _seed_inversion(a, b)
    bundles = [d for d in tmp_path.iterdir()
               if d.name.startswith("flight-")]
    assert len(bundles) == 1
    doc = load_bundle(str(bundles[0]))
    assert doc["manifest"]["reason"] == "lock_order"
    assert "lock order inversion" in doc["manifest"]["note"]
    assert "stacks" in doc and doc["stacks"]


def test_condition_wait_keeps_held_stack_truthful(witness_on):
    """Condition.wait releases through WitnessLock.release and re-acquires
    through acquire: during the wait the role is NOT held, so another
    lock taken by the woken thread sees the right stack."""
    cond = locks.make_condition("t.cond")
    other = locks.WitnessLock("t.other")
    with cond:
        cond.wait(0.01)
    # wait() ran release→acquire; the held stack must be balanced now
    with other:
        with cond:
            pass
    assert ("t.other -> t.cond") in locks.witness.edges()


def test_factory_is_plain_when_disabled():
    prev = locks.witness_enabled()
    locks.enable_witness(False)
    try:
        lk = locks.make_lock("t.plain")
        assert type(lk) is type(threading.Lock())
        cond = locks.make_condition("t.plain_cond")
        assert isinstance(cond, threading.Condition)
        assert not isinstance(cond._lock, locks.WitnessLock)
    finally:
        locks.enable_witness(prev)


def test_factory_is_witnessed_when_enabled(witness_on):
    lk = locks.make_lock("t.w")
    assert isinstance(lk, locks.WitnessLock)
    cond = locks.make_condition("t.wc")
    assert isinstance(cond._lock, locks.WitnessLock)


def test_graph_survives_threads(witness_on):
    """Edges recorded on one thread convict the opposite order on
    another — the graph is process-wide, the held stack per-thread."""
    a = locks.WitnessLock("t.a")
    b = locks.WitnessLock("t.b")

    def fwd():
        with a:
            with b:
                pass

    t = threading.Thread(target=fwd, name="witness-fwd")
    t.start()
    t.join()
    with pytest.raises(locks.LockOrderError):
        with b:
            with a:
                pass


def test_hot_cache_eviction_respects_hierarchy(witness_on):
    """Integration: the HotCache eviction path (the audited hot spot —
    slab frees now happen OUTSIDE the cache lock) plus pool recycling
    runs clean under the witness. Seeding the legal pool→cache order
    first makes any regression to free-under-lock an immediate raise."""
    from strom.delivery.buffers import SlabPool
    from strom.delivery.hotcache import HotCache

    pool = SlabPool(1 << 22)
    cache = HotCache(1 << 16, pool=pool, admit="always",
                     block_bytes=4096)
    import numpy as np

    data = np.zeros(1 << 15, dtype=np.uint8)
    # several admissions over one budget force evictions (and pool
    # releases) on the admit path; lookups pin/unpin around them
    for i in range(6):
        cache.admit(f"f{i}", 0, data.nbytes, data)
        hits, misses, pinned = cache.lookup(f"f{i}", 0, 4096)
        cache.unpin(pinned)
    cache.clear()
    assert cache.entries == 0
