"""MoE model + expert parallelism: routing invariants, capacity behavior,
ep-sharded training on a dp×ep mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from strom.models.moe import MoEConfig, forward, init_params, switch_route
from strom.parallel.mesh import make_mesh


class TestRouting:
    def test_dispatch_combine_shapes_and_mass(self):
        rng = np.random.default_rng(0)
        h = jnp.array(rng.normal(size=(64, 16)), jnp.float32)
        router = jnp.array(rng.normal(size=(16, 4)), jnp.float32)
        dispatch, combine, aux = switch_route(h, router, capacity=32)
        assert dispatch.shape == (64, 4, 32)
        # each token lands in at most one (expert, slot)
        per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
        assert set(per_token.tolist()) <= {0.0, 1.0}
        # each (expert, slot) holds at most one token
        per_slot = np.asarray(jnp.sum(dispatch, axis=0))
        assert per_slot.max() <= 1.0
        # combine mass = gate prob of kept tokens, <= 1
        assert float(jnp.sum(combine)) <= 64.0
        assert np.isfinite(np.asarray(aux)).all()

    def test_capacity_drops_overflow(self):
        # all tokens prefer expert 0 → only `capacity` survive
        h = jnp.ones((16, 4), jnp.float32)
        router = jnp.zeros((4, 2), jnp.float32).at[:, 0].set(10.0)
        dispatch, _, _ = switch_route(h, router, capacity=5)
        assert float(jnp.sum(dispatch)) == 5.0

    def test_balanced_router_keeps_everything(self):
        rng = np.random.default_rng(1)
        h = jnp.array(rng.normal(size=(64, 16)), jnp.float32)
        router = jnp.array(rng.normal(size=(16, 8)), jnp.float32)
        # capacity >= N: nothing can drop
        dispatch, _, _ = switch_route(h, router, capacity=64)
        np.testing.assert_allclose(np.asarray(jnp.sum(dispatch)), 64.0)


class TestMoEModel:
    @pytest.fixture(scope="class")
    def tiny(self):
        cfg = MoEConfig.tiny(n_experts=4)
        params = init_params(jax.random.PRNGKey(0), cfg)
        return cfg, params

    def test_forward_shapes_finite(self, tiny):
        cfg, params = tiny
        tokens = jnp.array(np.random.default_rng(0).integers(
            0, cfg.base.vocab, (2, 32)), jnp.int32)
        logits, aux = forward(params, tokens, cfg)
        assert logits.shape == (2, 32, cfg.base.vocab)
        assert bool(jnp.isfinite(logits).all())
        assert aux.shape == (2,) and bool(jnp.isfinite(aux).all())

    def test_causality(self, tiny):
        cfg, params = tiny
        rng = np.random.default_rng(1)
        t1 = rng.integers(0, cfg.base.vocab, (1, 24)).astype(np.int32)
        t2 = t1.copy()
        t2[0, 16:] = (t2[0, 16:] + 7) % cfg.base.vocab
        l1, _ = forward(params, jnp.array(t1), cfg)
        l2, _ = forward(params, jnp.array(t2), cfg)
        # NOTE: routing capacity couples tokens globally; use generous
        # capacity so early tokens' expert slots can't be stolen by changed
        # future tokens
        np.testing.assert_allclose(np.asarray(l1[0, :16]),
                                   np.asarray(l2[0, :16]), rtol=1e-3, atol=1e-3)

    def test_ep_sharded_training_decreases_loss(self):
        from strom.parallel.train import (init_moe_train_state,
                                          make_moe_train_step, make_optimizer)

        cfg = MoEConfig.tiny(n_experts=4)
        mesh = make_mesh({"dp": 2, "ep": 4}, devices=jax.devices()[:8])
        opt = make_optimizer(lr=1e-2, warmup=1)
        state = init_moe_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
        # expert stacks really live on the ep axis
        spec = state.params["layers"]["w_gate"].sharding.spec
        assert "ep" in spec
        step = make_moe_train_step(cfg, mesh, opt)
        tokens = jnp.array(np.random.default_rng(2).integers(
            0, cfg.base.vocab, (4, 33)), jnp.int32)
        losses = []
        for _ in range(5):
            state, metrics = step(state, tokens)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses

    def test_ep_with_sp_compose(self):
        """dp×sp×ep mesh: sequence-sharded batch + ep-sharded experts."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from strom.parallel.train import (init_moe_train_state,
                                          make_moe_train_step, make_optimizer)

        cfg = MoEConfig.tiny(n_experts=4)
        mesh = make_mesh({"dp": 2, "sp": 2, "ep": 2}, devices=jax.devices()[:8])
        opt = make_optimizer()
        state = init_moe_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
        step = make_moe_train_step(cfg, mesh, opt, sp=True)
        tokens = jnp.array(np.random.default_rng(3).integers(
            0, cfg.base.vocab, (4, 64)), jnp.int32)
        tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))
        state, metrics = step(state, tokens)
        assert np.isfinite(float(metrics["loss"]))

    def test_ep_with_sp_flash_ring(self):
        """dp×ep×sp with the Pallas flash kernels INSIDE the ring: the
        expert all-to-alls and the ring's kv ppermutes coexist on one mesh
        (mirrors the dryrun's 8th config)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from strom.parallel.train import (init_moe_train_state,
                                          make_moe_train_step, make_optimizer)

        cfg = MoEConfig.tiny(n_experts=4)
        mesh = make_mesh({"dp": 2, "ep": 2, "sp": 2}, devices=jax.devices()[:8])
        opt = make_optimizer()
        state = init_moe_train_state(jax.random.PRNGKey(1), cfg, mesh, opt)
        step = make_moe_train_step(cfg, mesh, opt, sp=True, attn="flash")
        tokens = jnp.array(np.random.default_rng(4).integers(
            0, cfg.base.vocab, (4, 64)), jnp.int32)
        tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))
        state, metrics = step(state, tokens)
        assert np.isfinite(float(metrics["loss"]))
