"""Joint train-state + loader-state checkpointing: a restore resumes BOTH the
model and the exact data cursor (SURVEY.md §5 'Checkpoint/resume')."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.models.llama import LlamaConfig
from strom.parallel.mesh import make_mesh
from strom.parallel.train import init_train_state, make_optimizer, make_train_step
from strom.pipelines import make_llama_pipeline
from strom.pipelines.checkpoint import TrainCheckpointer


def abstract_like(cfg, mesh, opt):
    """Abstract train-state pytree (shapes + shardings) for ck.restore —
    shared so the recipe lives in one place."""
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state)


@pytest.fixture(scope="module")
def token_paths(tmp_path_factory):
    td = tmp_path_factory.mktemp("ckpt_tokens")
    rng = np.random.default_rng(7)
    paths = []
    for i in range(2):
        p = str(td / f"s{i}.bin")
        rng.integers(0, 500, 17 * 40, dtype=np.int32).tofile(p)
        paths.append(p)
    return paths


def test_save_restore_resumes_exact_trajectory(tmp_path, token_paths):
    cfg = LlamaConfig.tiny()
    mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    sharding = NamedSharding(mesh, P("dp", None))
    opt = make_optimizer()
    step = make_train_step(cfg, mesh, opt, donate=False)
    ctx = StromContext(StromConfig(engine="python", queue_depth=8, num_buffers=8))
    ck = TrainCheckpointer(str(tmp_path / "ckpts"))
    try:
        # run 3 steps, checkpoint at 2, run 1 more; record the 4th batch loss
        state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
        with make_llama_pipeline(ctx, token_paths, batch=8, seq_len=16,
                                 sharding=sharding, seed=5) as pipe:
            for i in range(1, 4):
                state, metrics = step(state, next(pipe))
                if i == 2:
                    ck.save(2, state, pipe, {"note": "mid"})
            loss_step3 = float(metrics["loss"])

        assert ck.latest_step() == 2
        abstract = abstract_like(cfg, mesh, opt)
        restored, sampler_state, extra = ck.restore(2, abstract)
        assert extra == {"note": "mid"}
        assert int(restored.step) == 2
        # resume via the file path: fingerprint + seed validated
        with make_llama_pipeline(ctx, token_paths, batch=8, seq_len=16,
                                 sharding=sharding, seed=5,
                                 resume_from=ck.loader_state_path(2)) as pipe2:
            restored, metrics2 = step(restored, next(pipe2))
        # same params + same batch ⇒ bit-identical continuation
        assert float(metrics2["loss"]) == loss_step3
        assert int(restored.step) == 3
    finally:
        ck.close()
        ctx.close()


def test_resume_against_changed_dataset_rejected(tmp_path, token_paths):
    """The checkpoint's loader blob must refuse a changed shard list."""
    ctx = StromContext(StromConfig(engine="python", queue_depth=8, num_buffers=8))
    mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    sharding = NamedSharding(mesh, P("dp", None))
    try:
        with make_llama_pipeline(ctx, token_paths, batch=8, seq_len=16,
                                 sharding=sharding, seed=5) as pipe:
            next(pipe)
            f = str(tmp_path / "loader.json")
            pipe.save_state(f)
        grown = str(tmp_path / "extra.bin")
        np.random.default_rng(1).integers(0, 500, 17 * 10, dtype=np.int32).tofile(grown)
        with pytest.raises(ValueError, match="different dataset"):
            make_llama_pipeline(ctx, list(token_paths) + [grown], batch=8,
                                seq_len=16, sharding=sharding, seed=5,
                                resume_from=f)
    finally:
        ctx.close()


def test_async_save_captures_cursor_at_call(tmp_path, token_paths):
    """save(blocking=False): the loader cursor saved is the one AT the call —
    batches consumed while the checkpoint drains must not leak into it — and
    latest_step() only reports the step once fully committed."""
    cfg = LlamaConfig.tiny()
    mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    sharding = NamedSharding(mesh, P("dp", None))
    opt = make_optimizer()
    step = make_train_step(cfg, mesh, opt, donate=False)
    ctx = StromContext(StromConfig(engine="python", queue_depth=8, num_buffers=8))
    ck = TrainCheckpointer(str(tmp_path / "ckpts"))
    try:
        state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
        with make_llama_pipeline(ctx, token_paths, batch=8, seq_len=16,
                                 sharding=sharding, seed=5) as pipe:
            state, _ = step(state, next(pipe))
            ck.save(1, state, pipe, blocking=False)
            expected = pipe.state()  # the resume point at the save call
            # training races ahead while the checkpoint drains
            for _ in range(2):
                state, _ = step(state, next(pipe))
            ck.wait_until_finished()
        assert ck.latest_step() == 1
        from strom.pipelines.sampler import load_loader_state

        saved, _ = load_loader_state(ck.loader_state_path(1))
        assert saved == expected
    finally:
        ck.close()
        ctx.close()


def test_async_commit_failure_surfaces(tmp_path, token_paths, monkeypatch):
    """A failed background commit must raise at the next join point, not
    report success and strand the operator at resume time."""
    import strom.pipelines.checkpoint as cmod

    cfg = LlamaConfig.tiny()
    mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    sharding = NamedSharding(mesh, P("dp", None))
    opt = make_optimizer()
    ctx = StromContext(StromConfig(engine="python", queue_depth=8, num_buffers=8))
    ck = TrainCheckpointer(str(tmp_path / "ckpts"))
    try:
        state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
        monkeypatch.setattr(cmod, "save_loader_state",
                            lambda *a, **k: (_ for _ in ()).throw(OSError(28, "disk full")))
        with make_llama_pipeline(ctx, token_paths, batch=8, seq_len=16,
                                 sharding=sharding, seed=5) as pipe:
            next(pipe)
            ck.save(1, state, pipe, blocking=False)
            with pytest.raises(RuntimeError, match="checkpoint commit failed"):
                ck.wait_until_finished()
        assert ck.latest_step() is None  # no torn checkpoint visible
    finally:
        ck.close()
        ctx.close()


def test_pp_sharded_state_roundtrips(tmp_path, token_paths):
    """Pipeline-parallel (pp-sharded layer stacks) train states must survive
    save/restore with their shardings re-placed, like every other mesh."""
    from strom.parallel.pipeline import make_pp_train_step

    cfg = LlamaConfig.tiny()
    mesh = make_mesh({"dp": 4, "pp": 2}, devices=jax.devices()[:8])
    sharding = NamedSharding(mesh, P("dp", None))
    opt = make_optimizer()
    step = make_pp_train_step(cfg, mesh, opt, donate=False, microbatches=2)
    ctx = StromContext(StromConfig(engine="python", queue_depth=8, num_buffers=8))
    ck = TrainCheckpointer(str(tmp_path / "ckpts"))
    try:
        state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
        with make_llama_pipeline(ctx, token_paths, batch=8, seq_len=16,
                                 sharding=sharding, seed=5) as pipe:
            state, m1 = step(state, next(pipe))
            ck.save(1, state, pipe)
            batch2 = next(pipe)
            state, m2 = step(state, batch2)
        abstract = abstract_like(cfg, mesh, opt)
        restored, _, _ = ck.restore(1, abstract)
        assert int(restored.step) == 1
        wq = restored.params["layers"]["wq"]
        assert wq.sharding.spec[0] == "pp"  # sharding re-placed, not flattened
        # same params + same batch ⇒ identical continuation
        restored, m2b = step(restored, batch2)
        assert float(m2b["loss"]) == float(m2["loss"])
    finally:
        ck.close()
        ctx.close()


def test_latest_step_ignores_incomplete(tmp_path, token_paths):
    import os

    ck = TrainCheckpointer(str(tmp_path / "ckpts"))
    os.makedirs(str(tmp_path / "ckpts" / "00000005"))  # no loader blob: torn
    assert ck.latest_step() is None
    ck.close()
