"""Engine integration on real files: integrity, alignment fallbacks, EOF,
queue-depth pipelining, faults, stats (SURVEY.md §4.2 Engine/Integrity rows).
Runs against both the C++ io_uring engine and the Python fallback."""

import os

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.buffers import alloc_aligned
from strom.engine import make_engine
from strom.engine.base import EngineError, RawRead, ReadRequest


@pytest.fixture()
def engine(engine_name):
    cfg = StromConfig(engine=engine_name, queue_depth=16, num_buffers=16)
    eng = make_engine(cfg)
    assert eng.name == engine_name
    yield eng
    eng.close()


def test_pool_read_integrity(engine, data_file):
    path, data = data_file
    fi = engine.register_file(path)
    out = np.zeros(len(data), dtype=np.uint8)
    n = engine.read_into(fi, 0, len(data), out)
    assert n == len(data)
    np.testing.assert_array_equal(out, data)


@pytest.mark.parametrize("offset,length", [
    (0, 4096),          # aligned
    (1234, 100_000),    # unaligned offset+length
    (4096, 128 * 1024), # one full block
    (0, 1),             # tiny
])
def test_ranged_reads(engine, data_file, offset, length):
    path, data = data_file
    fi = engine.register_file(path)
    out = np.zeros(length, dtype=np.uint8)
    n = engine.read_into(fi, offset, length, out)
    assert n == length
    np.testing.assert_array_equal(out, data[offset:offset + length])


def test_eof_short_read(engine, data_file):
    path, data = data_file
    fi = engine.register_file(path)
    out = np.zeros(8192, dtype=np.uint8)
    n = engine.read_into(fi, len(data) - 1000, 8192, out)
    assert n == 1000
    np.testing.assert_array_equal(out[:1000], data[-1000:])


def test_raw_slab_read(engine, data_file):
    path, data = data_file
    fi = engine.register_file(path)
    dest = alloc_aligned(len(data))
    n = engine.read_into_direct(fi, 0, len(data), dest)
    assert n == len(data)
    np.testing.assert_array_equal(dest, data)


def test_queue_depth_enforced(engine, data_file):
    path, _ = data_file
    fi = engine.register_file(path)
    # submit up to depth; the next one must fail with EAGAIN
    reqs = [ReadRequest(fi, i * 4096, 4096, i % engine.num_buffers, i)
            for i in range(engine.config.queue_depth)]
    engine.submit(reqs)
    with pytest.raises(EngineError):
        engine.submit([ReadRequest(fi, 0, 4096, 0, 999)])
    got = []
    while len(got) < len(reqs):
        got.extend(engine.wait(min_completions=1, timeout_s=10))
    assert sorted(c.tag for c in got) == sorted(r.tag for r in reqs)


def test_completion_tags_and_buffers(engine, data_file):
    path, data = data_file
    fi = engine.register_file(path)
    engine.submit([ReadRequest(fi, 8192, 4096, 3, tag=42)])
    (c,) = engine.wait(min_completions=1, timeout_s=10)
    assert c.tag == 42 and c.result == 4096
    np.testing.assert_array_equal(engine.buffer(3)[:4096], data[8192:8192 + 4096])


def test_fault_injection(engine_name, data_file):
    path, _ = data_file
    cfg = StromConfig(engine=engine_name, queue_depth=8, num_buffers=8, fault_every=2)
    eng = make_engine(cfg)
    try:
        fi = eng.register_file(path)
        results = []
        for i in range(8):
            eng.submit([ReadRequest(fi, 0, 4096, i % 8, i)])
            (c,) = eng.wait(min_completions=1, timeout_s=10)
            results.append(c.result)
        errors = [r for r in results if r < 0]
        assert len(errors) == 4  # every 2nd op faults with -EIO
        assert all(r == -5 for r in errors)
        assert eng.stats()["ops_faulted"] == 4
    finally:
        eng.close()


def test_stats_accounting(engine, data_file):
    path, data = data_file
    fi = engine.register_file(path)
    out = np.zeros(len(data), dtype=np.uint8)
    engine.read_into(fi, 0, len(data), out)
    s = engine.stats()
    assert s["bytes_read"] >= len(data)
    assert s["ops_completed"] >= len(data) // engine.config.block_size
    assert s["in_flight"] == 0


def test_coop_taskrun_knob(data_file):
    """coop_taskrun=True sets IORING_SETUP_COOP_TASKRUN (this CI kernel is
    5.19+ so it must actually engage); =False must leave it off; reads work
    identically either way."""
    from strom.config import StromConfig
    from strom.engine import make_engine
    from strom.engine.uring_engine import UringEngine

    path, data = data_file
    for coop in (True, False):
        eng = make_engine(StromConfig(coop_taskrun=coop, queue_depth=8,
                                      num_buffers=8))
        if not isinstance(eng, UringEngine):
            eng.close()
            return  # python fallback engine: knob is uring-only
        try:
            assert eng.stats()["coop_taskrun"] is coop
            fi = eng.register_file(path)
            out = np.zeros(8192, dtype=np.uint8)
            assert eng.read_into(fi, 0, 8192, out) == 8192
            np.testing.assert_array_equal(out, np.frombuffer(
                bytes(data[:8192]), dtype=np.uint8))
        finally:
            eng.close()


class TestRegisteredDest:
    """READ_FIXED into caller slabs (VERDICT.md missing #1: 'registered
    fixed buffers are dead in the hot path'): register delivery slabs in the
    ring's sparse table; vectored gathers into them must ride the fixed
    opcode and return identical bytes."""

    @pytest.fixture()
    def uring(self):
        from strom.engine.uring_engine import UringEngine, uring_available

        if not uring_available():
            pytest.skip("io_uring unavailable")
        eng = UringEngine(StromConfig(queue_depth=16, num_buffers=16))
        if not eng.stats().get("sparse_table"):
            eng.close()
            pytest.skip("kernel lacks sparse BUFFERS2")
        yield eng
        eng.close()

    def test_register_read_unregister(self, uring, data_file):
        path, data = data_file
        # evict the just-written pages: a warm file rides the hybrid's
        # buffered path (no READ_FIXED), and this test asserts the O_DIRECT
        # fixed-buffer arm specifically
        from strom.probe.residency import drop_cache

        drop_cache(path)
        fi = uring.register_file(path)
        slab = alloc_aligned(len(data))
        idx = uring.register_dest(slab)
        assert idx >= uring.config.num_buffers  # external slot
        assert uring.stats()["ext_buffers"] == 1
        n = uring.read_vectored([(fi, 0, 0, len(data))], slab)
        assert n == len(data)
        np.testing.assert_array_equal(slab, data)
        assert uring.stats()["ops_fixed"] > 0  # the gather rode READ_FIXED
        uring.unregister_dest(slab)
        assert uring.stats()["ext_buffers"] == 0
        # unregistered: same gather still works via plain READ
        slab[:] = 0
        assert uring.read_vectored([(fi, 0, 0, len(data))], slab) == len(data)
        np.testing.assert_array_equal(slab, data)

    def test_partial_range_and_offset_reads(self, uring, data_file):
        """READ_FIXED with addr strictly inside the registered entry."""
        path, data = data_file
        fi = uring.register_file(path)
        slab = alloc_aligned(1 << 20)
        uring.register_dest(slab)
        n = uring.read_vectored([(fi, 4096, 8192, 65536),
                                 (fi, 100_000, 200_000, 33_333)], slab)
        assert n == 65536 + 33_333
        np.testing.assert_array_equal(slab[8192:8192 + 65536],
                                      data[4096:4096 + 65536])
        np.testing.assert_array_equal(slab[200_000:200_000 + 33_333],
                                      data[100_000:100_000 + 33_333])

    def test_slot_exhaustion_degrades(self, uring):
        slabs = [alloc_aligned(4096) for _ in range(70)]
        idxs = [uring.register_dest(s) for s in slabs]
        assert sum(1 for i in idxs if i >= 0) == 64  # table capacity
        assert all(i == -1 for i in idxs[64:])       # graceful, no raise

    def test_pool_slab_autoregisters_in_context(self, data_file):
        from strom.config import StromConfig
        from strom.delivery.core import StromContext
        from strom.engine.uring_engine import UringEngine, uring_available

        if not uring_available():
            pytest.skip("io_uring unavailable")
        path, data = data_file
        ctx = StromContext(StromConfig(engine="uring", queue_depth=16,
                                       num_buffers=16))
        try:
            if not isinstance(ctx.engine, UringEngine) or \
                    not ctx.engine.stats().get("sparse_table"):
                pytest.skip("sparse table unavailable")
            assert ctx._slab_pool is not None
            slab = ctx._slab_pool.acquire(1 << 20)
            assert ctx.engine.stats()["ext_buffers"] >= 1
            fi = ctx.engine.register_file(path)
            n = ctx.engine.read_vectored([(fi, 0, 0, 1 << 20)], slab)
            assert n == 1 << 20
            np.testing.assert_array_equal(slab, data[: 1 << 20])
            ctx._slab_pool.release(slab)
        finally:
            ctx.close()


def test_o_direct_denied_falls_back(engine, tmp_path):
    """/proc files refuse O_DIRECT; registration must degrade, not fail."""
    fi = engine.register_file("/proc/self/status")
    assert engine.file_uses_o_direct(fi) is False
    out = np.zeros(64, dtype=np.uint8)
    n = engine.read_into(fi, 0, 64, out)
    assert n > 0


def test_unregister_file(engine, data_file):
    path, _ = data_file
    fi = engine.register_file(path)
    engine.unregister_file(fi)
    with pytest.raises(Exception):
        engine.file_uses_o_direct(fi)


class TestReadVectored:
    """Engine-level gather API (native in C++ engine, generic fallback)."""

    def test_gather_integrity(self, engine, data_file):
        path, data = data_file
        fi = engine.register_file(path)
        chunks = [(fi, 100_000, 0, 300_000),   # spans blocks, unaligned
                  (fi, 0, 300_000, 4096),
                  (fi, 2_000_000, 304_096, 1_000_001)]
        dest = alloc_aligned(304_096 + 1_000_001)
        n = engine.read_vectored(chunks, dest)
        assert n == dest.nbytes
        want = np.concatenate([data[100_000:400_000], data[:4096],
                               data[2_000_000:3_000_001]])
        np.testing.assert_array_equal(dest, want)

    def test_empty_chunks(self, engine):
        assert engine.read_vectored([], alloc_aligned(16)) == 0

    def test_short_read_is_enodata(self, engine, data_file):
        import errno

        path, data = data_file
        fi = engine.register_file(path)
        dest = alloc_aligned(1 << 20)
        with pytest.raises(EngineError) as ei:
            engine.read_vectored([(fi, len(data) - 100, 0, 1 << 20)], dest)
        assert ei.value.errno == errno.ENODATA

    def test_dest_too_small_rejected(self, engine, data_file):
        path, _ = data_file
        fi = engine.register_file(path)
        dest = alloc_aligned(1024)
        with pytest.raises(EngineError):
            engine.read_vectored([(fi, 0, 0, 1 << 20)], dest)

    def test_retry_budget_respected(self, engine, data_file):
        if not hasattr(engine, "set_fault_every"):
            import dataclasses
            object.__setattr__(engine.config, "fault_every", 1)
        else:
            engine.set_fault_every(1)
        path, _ = data_file
        fi = engine.register_file(path)
        dest = alloc_aligned(512 * 1024)
        with pytest.raises(EngineError, match="after 3 attempts"):
            engine.read_vectored([(fi, 0, 0, 512 * 1024)], dest, retries=2)


def test_sqpoll_knob(data_file):
    """sqpoll=True asks for an IORING_SETUP_SQPOLL ring (kernel thread polls
    the SQ; zero io_uring_enter per submitted batch). The kernel may refuse
    it (privileges, rlimits) — then the engine must fall back silently and
    reads must be identical either way. When it IS active, a full vectored
    gather must complete through the poller thread (including the
    need-wakeup path after the poller idles)."""
    import time as _time

    from strom.config import StromConfig
    from strom.delivery.buffers import alloc_aligned
    from strom.engine import make_engine
    from strom.engine.uring_engine import UringEngine

    path, data = data_file
    eng = make_engine(StromConfig(sqpoll=True, queue_depth=8, num_buffers=8))
    if not isinstance(eng, UringEngine):
        eng.close()
        return  # python fallback engine: knob is uring-only
    try:
        active = eng.stats()["sqpoll"]
        fi = eng.register_file(path)
        n = 1 << 20
        dest = alloc_aligned(n)
        assert eng.read_vectored([(fi, 0, 0, n)], dest) == n
        np.testing.assert_array_equal(dest, data[:n])
        if active:
            # second gather after a pause still works (exercises the
            # IORING_SQ_NEED_WAKEUP arm once sq_thread_idle elapses; the
            # 1.2s sleep matches the engine's 1000ms idle setting)
            _time.sleep(1.2)
            dest2 = alloc_aligned(n)
            assert eng.read_vectored([(fi, n, 0, n)], dest2) == n
            np.testing.assert_array_equal(dest2, data[n:2 * n])
    finally:
        eng.close()
