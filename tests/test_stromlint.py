"""tools/stromlint wired in as a tier-1 gate (ISSUE 11): the REPO's own
tree must lint clean under every pass (lock-order vs the canonical
hierarchy, blocking-under-lock, thread-lifecycle, errno-exhaustiveness,
swallowed-exceptions, pragma justification), and each rule must actually
catch its synthetic bad module under tests/lint_fixtures/ — a clean
result must mean "disciplined", never "nothing scanned"."""

import json
import os
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.stromlint import main, run_rules  # noqa: E402
from tools.stromlint.core import RULES  # noqa: E402

_FIX = os.path.join(_ROOT, "tests", "lint_fixtures")


def _fixture_findings(fixture: str, rule: str):
    doc = run_rules(_ROOT, select=[rule, "pragma"],
                    paths=[os.path.join(_FIX, fixture)])
    return doc["findings"]


# -- the tree is clean --------------------------------------------------------

def test_repo_is_clean():
    assert main([_ROOT, "--check"]) == 0


def test_repo_scan_actually_saw_the_tree():
    doc = run_rules(_ROOT)
    # the hierarchy adoption is real: dozens of make_lock declarations
    assert doc["files"] > 50
    assert doc["locks"] > 25
    # and the clean result rode justified pragmas, not an empty scan
    assert doc["suppressed"] > 0
    assert doc["ok"]


# -- lock-order ---------------------------------------------------------------

def test_lock_order_catches_inversion():
    msgs = [f.message for f in _fixture_findings("bad_lock_order.py",
                                                 "lock-order")]
    assert any("inversion" in m and "slab.pool" in m and "cache.meta" in m
               for m in msgs)


def test_lock_order_catches_undeclared_pair():
    msgs = [f.message for f in _fixture_findings("bad_lock_order.py",
                                                 "lock-order")]
    assert any("undeclared lock pair" in m and "_mystery_lock" in m
               for m in msgs)


def test_lock_order_catches_unscoped_acquire():
    msgs = [f.message for f in _fixture_findings("bad_lock_order.py",
                                                 "lock-order")]
    assert any("outside a with-statement" in m for m in msgs)


def test_lock_order_sees_through_helpers():
    """The interprocedural half: a helper that takes the pool lock makes
    its cache-lock-holding caller an inversion (the HotCache eviction
    shape this pass exists to keep fixed)."""
    finds = _fixture_findings("bad_lock_order.py", "lock-order")
    helper_lines = [f for f in finds if "helper" in f.message]
    assert helper_lines, [f.message for f in finds]


# -- blocking-under-lock ------------------------------------------------------

def test_blocking_catches_each_shape():
    msgs = [f.message for f in _fixture_findings("bad_blocking.py",
                                                 "blocking-under-lock")]
    assert any("time.sleep" in m for m in msgs)
    assert any(".wait()" in m for m in msgs)
    assert any(".get()" in m for m in msgs)
    assert any(".result()" in m for m in msgs)
    assert any("open()" in m for m in msgs)
    assert any(".poll()" in m for m in msgs)


def test_blocking_accepts_bounded_waits():
    finds = _fixture_findings("bad_blocking.py", "blocking-under-lock")
    # everything flagged lives in bad(); fine() has timeouts everywhere
    with open(os.path.join(_FIX, "bad_blocking.py")) as f:
        src = f.read().split("\n")
    fine_start = next(i for i, l in enumerate(src, 1)
                      if l.startswith("def fine"))
    assert all(f.line < fine_start for f in finds)


# -- thread-lifecycle ---------------------------------------------------------

def test_threads_catch_anonymous_and_unreclaimed():
    msgs = [f.message for f in _fixture_findings("bad_threads.py",
                                                 "thread-lifecycle")]
    assert any("without name=" in m for m in msgs)
    assert any("neither daemon=True nor joined" in m for m in msgs)


# -- errno-exhaustiveness -----------------------------------------------------

def test_errnos_catch_unclassified():
    doc = run_rules(os.path.join(_FIX, "errno_tree"),
                    select=["errno-exhaustiveness"])
    msgs = [f.message for f in doc["findings"]]
    assert any("EOWNERDEAD" in m for m in msgs)
    # EIO and ETIMEDOUT are classified; only the sneaky one fails
    assert not any("EIO " in m for m in msgs)
    assert not any("ETIMEDOUT" in m for m in msgs)


# -- swallowed-exceptions -----------------------------------------------------

def test_excepts_catch_silent_swallow_only():
    finds = _fixture_findings("bad_excepts.py", "swallowed-exceptions")
    assert len(finds) == 1  # swallow(); counted() and reraised() pass
    assert "neither re-raises nor marks" in finds[0].message


# -- pragmas ------------------------------------------------------------------

def test_pragma_without_reason_is_a_finding():
    finds = _fixture_findings("pragmas.py", "swallowed-exceptions")
    assert [f.rule for f in finds] == ["pragma"]
    assert "without a reason" in finds[0].message


def test_justified_pragma_suppresses():
    doc = run_rules(_ROOT, select=["swallowed-exceptions", "pragma"],
                    paths=[os.path.join(_FIX, "pragmas.py")])
    # two swallows; the justified one vanished into the suppressed count
    assert doc["suppressed"] >= 1


# -- CLI surface --------------------------------------------------------------

def test_json_output(capsys):
    rc = main([_ROOT, "--json", "--select", "thread-lifecycle"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True
    assert doc["findings"] == []
    assert doc["files"] > 50


def test_select_unknown_rule_is_usage_error():
    assert main([_ROOT, "--select", "no-such-rule"]) == 2


def test_fixture_run_fails_check(capsys):
    rc = main([_ROOT, "--check", "--select", "swallowed-exceptions",
               "--paths", os.path.join(_FIX, "bad_excepts.py")])
    assert rc == 1


def test_rules_list_is_stable():
    assert set(RULES) >= {"lock-order", "blocking-under-lock",
                          "thread-lifecycle", "errno-exhaustiveness",
                          "swallowed-exceptions", "pragma"}


def test_deliberate_inversion_in_real_module_is_caught(tmp_path):
    """Acceptance: a deliberately introduced inversion in a strom-shaped
    module fails the lock-order pass (the static half; the dynamic half
    is tests/test_locks.py's seeded WitnessLock inversion)."""
    mod = tmp_path / "inverted.py"
    mod.write_text(
        "from strom.utils.locks import make_lock\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._pool_lock = make_lock('slab.pool')\n"
        "        self._sched_cond = make_lock('sched.arbiter')\n"
        "    def bad(self):\n"
        "        with self._pool_lock:\n"
        "            with self._sched_cond:\n"
        "                pass\n")
    doc = run_rules(_ROOT, select=["lock-order"], paths=[str(mod)])
    assert not doc["ok"]
    assert any("inversion" in f.message for f in doc["findings"])
