"""Submission-boundary failure handling in the C++ engine.

Round-1 review findings (VERDICT.md weak #2, ADVICE.md): a fatal errno from
io_uring_enter used to leave published-but-never-submitted ops accounted as
in-flight, so sc_wait(timeout=-1) would block forever on completions the
kernel would never produce. The fix rolls the SQEs back and fails the ops via
synthetic completions; these tests force that path with the
sc_set_enter_fail_once hook (≙ sc_set_fault_every for the submit boundary).
Also covers the uint32 chunk-length splitting that prevents silent ctypes
truncation of >=4GiB gather chunks (ADVICE.md high).
"""

import errno

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.buffers import alloc_aligned
from strom.engine import make_engine
from strom.engine.base import EngineError, RawRead
from strom.engine.uring_engine import _MAX_SEG, _split_chunks, uring_available

pytestmark = pytest.mark.skipif(not uring_available(),
                                reason="io_uring unavailable in this sandbox")


@pytest.fixture()
def engine():
    cfg = StromConfig(engine="uring", queue_depth=8, num_buffers=8)
    eng = make_engine(cfg)
    yield eng
    eng.close()


class TestEnterFailure:
    def test_failure_surfaces_within_one_wait(self, engine, data_file):
        """A fatal submit errno must complete the op with that errno (via a
        synthetic completion) — not strand it in in_flight forever."""
        path, _ = data_file
        fi = engine.register_file(path)
        dest = alloc_aligned(128 * 1024)
        engine.set_enter_fail_once(errno.EIO)
        engine.submit_raw([RawRead(fi, 0, 128 * 1024, dest, tag=7)])
        # timeout bounds the test: pre-fix this wait hung forever
        comps = engine.wait(min_completions=1, timeout_s=5.0)
        assert len(comps) == 1
        assert comps[0].tag == 7
        assert comps[0].result == -errno.EIO
        assert engine.in_flight() == 0

    def test_batch_rollback_fails_all_ops(self, engine, data_file):
        """Every op of a batch the kernel never saw gets a failure completion."""
        path, _ = data_file
        fi = engine.register_file(path)
        dests = [alloc_aligned(64 * 1024) for _ in range(4)]
        engine.set_enter_fail_once(errno.ENOMEM)
        engine.submit_raw([RawRead(fi, i * 65536, 65536, d, tag=100 + i)
                           for i, d in enumerate(dests)])
        comps = engine.wait(min_completions=4, timeout_s=5.0)
        assert sorted(c.tag for c in comps) == [100, 101, 102, 103]
        assert all(c.result == -errno.ENOMEM for c in comps)
        assert engine.in_flight() == 0

    def test_vectored_retry_recovers(self, engine, data_file):
        """read_vectored's per-chunk retry absorbs a one-shot submit failure
        transparently: data stays golden."""
        path, golden = data_file
        fi = engine.register_file(path)
        dest = alloc_aligned(1024 * 1024)
        engine.set_enter_fail_once(errno.EIO)
        n = engine.read_vectored([(fi, 0, 0, 1024 * 1024)], dest, retries=1)
        assert n == 1024 * 1024
        np.testing.assert_array_equal(dest, golden[: 1024 * 1024])

    def test_vectored_no_retry_fails_loudly(self, engine, data_file):
        path, golden = data_file
        fi = engine.register_file(path)
        dest = alloc_aligned(1024 * 1024)
        engine.set_enter_fail_once(errno.EIO)
        with pytest.raises(EngineError):
            engine.read_vectored([(fi, 0, 0, 1024 * 1024)], dest, retries=0)
        # engine must stay usable: the rollback freed every slot
        assert engine.in_flight() == 0
        n = engine.read_vectored([(fi, 0, 0, 1024 * 1024)], dest, retries=0)
        assert n == 1024 * 1024
        np.testing.assert_array_equal(dest, golden[: 1024 * 1024])


class TestCrossThreadWake:
    def test_waiter_thread_sees_synthetic_completion(self, engine, data_file):
        """A dedicated waiter thread must observe a synthetic (rollback)
        completion submitted by another thread even though it produces no
        kernel CQE: the infinite-wait arm polls the synthetic queue on a
        bounded cadence instead of parking forever in IORING_ENTER_GETEVENTS."""
        import threading
        import time

        path, _ = data_file
        fi = engine.register_file(path)
        got: list = []

        def waiter():
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                comps = engine.wait(min_completions=1, timeout_s=None)
                if comps:  # wait() returns [] fast while nothing is in flight
                    got.extend(comps)
                    return
                time.sleep(0.001)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)  # let the waiter reach its wait loop first
        dest = alloc_aligned(64 * 1024)
        engine.set_enter_fail_once(errno.EIO)
        engine.submit_raw([RawRead(fi, 0, 65536, dest, tag=42)])
        t.join(timeout=5.0)
        assert not t.is_alive(), "waiter stranded on synthetic completion"
        assert [c.tag for c in got] == [42]
        assert got[0].result == -errno.EIO


class TestChunkSplitting:
    def test_small_chunks_pass_through(self):
        chunks = [(0, 0, 0, 4096), (1, 8192, 4096, 128 * 1024)]
        assert _split_chunks(chunks) == chunks

    def test_oversized_chunk_split(self):
        ln = 5 * (1 << 30)  # 5 GiB: ctypes would mask this to 1 GiB
        out = _split_chunks([(0, 0, 0, ln)])
        assert sum(c[3] for c in out) == ln
        assert all(c[3] <= _MAX_SEG for c in out)
        # pieces must tile the original range contiguously in file AND dest
        pos = 0
        for fi, fo, do, l in out:
            assert fi == 0 and fo == pos and do == pos
            pos += l
        assert pos == ln

    def test_exact_limit_not_split(self):
        assert _split_chunks([(0, 0, 0, _MAX_SEG)]) == [(0, 0, 0, _MAX_SEG)]

    def test_negative_length_rejected(self):
        with pytest.raises(ValueError):
            _split_chunks([(0, 0, 0, -1)])

    def test_raw_read_overflow_rejected(self, engine, data_file):
        path, _ = data_file
        fi = engine.register_file(path)
        dest = np.zeros(8, dtype=np.uint8)  # size check is on length field
        with pytest.raises(EngineError, match="uint32"):
            engine.submit_raw([RawRead(fi, 0, 1 << 33, dest, tag=1)])


class TestBatchSubmit:
    def test_multi_request_batch(self, engine, data_file):
        """submit_raw of N requests lands them all (one enter per batch)."""
        path, golden = data_file
        fi = engine.register_file(path)
        dests = [alloc_aligned(64 * 1024) for _ in range(6)]
        engine.submit_raw([RawRead(fi, i * 65536, 65536, d, tag=i)
                           for i, d in enumerate(dests)])
        got = {}
        while len(got) < 6:
            for c in engine.wait(min_completions=1, timeout_s=5.0):
                got[c.tag] = c.result
        assert all(v == 65536 for v in got.values())
        for i, d in enumerate(dests):
            np.testing.assert_array_equal(d, golden[i * 65536:(i + 1) * 65536])
