"""RAID0 stripe math: pure-function property tests (SURVEY.md §4.2 Unit row)."""

import os
import numpy as np
import pytest

from strom.engine.raid0 import StripeSegment, coalesce, logical_size, plan_stripe_reads


def reference_byte_map(offset, length, n, chunk):
    """Brute-force per-byte mapping to validate the closed form."""
    out = []
    for pos in range(offset, offset + length):
        chunk_idx = pos // chunk
        member = chunk_idx % n
        member_off = (chunk_idx // n) * chunk + pos % chunk
        out.append((member, member_off))
    return out


@pytest.mark.parametrize("offset,length,n,chunk", [
    (0, 1024, 2, 256),
    (100, 1000, 3, 256),
    (255, 2, 4, 256),
    (0, 10_000, 4, 512),
    (4096, 128 * 1024, 4, 64 * 1024),
    (7, 1, 1, 512),
])
def test_stripe_plan_matches_bytemap(offset, length, n, chunk):
    segs = plan_stripe_reads(offset, length, n, chunk)
    # reconstruct the byte map from segments
    recon = {}
    for s in segs:
        for i in range(s.length):
            recon[s.logical_offset + i] = (s.member, s.member_offset + i)
    expected = reference_byte_map(offset, length, n, chunk)
    for i, pos in enumerate(range(offset, offset + length)):
        assert recon[pos] == expected[i]
    # segments ordered by logical offset and exactly cover the range
    assert sum(s.length for s in segs) == length
    assert segs == sorted(segs, key=lambda s: s.logical_offset)


def test_stripe_single_member_is_identity():
    segs = plan_stripe_reads(123, 4567, 1, 512)
    segs = coalesce(segs)
    assert len(segs) == 1
    assert segs[0] == StripeSegment(0, 123, 123, 4567)


def test_coalesce_merges_adjacent():
    segs = plan_stripe_reads(0, 4 * 512, 1, 512)
    assert len(coalesce(segs)) == 1


def test_logical_size():
    assert logical_size([1000, 1000], 256) == 2 * 768
    assert logical_size([], 256) == 0
    assert logical_size([256], 256) == 256


def test_stripe_read_integrity_over_files(tmp_path, rng):
    """Write a striped logical image over 3 member files, then reassemble via
    the plan and compare to the logical original."""
    n, chunk = 3, 4096
    logical = rng.integers(0, 256, size=10 * chunk * n + 1234, dtype=np.uint8)
    # build members from the logical image using the same math the kernel uses
    member_data = [bytearray() for _ in range(n)]
    pos = 0
    while pos < len(logical):
        take = min(chunk, len(logical) - pos)
        m = (pos // chunk) % n
        member_data[m].extend(logical[pos:pos + take])
        pos += take
    paths = []
    for i, md in enumerate(member_data):
        p = tmp_path / f"member{i}.bin"
        with open(p, "wb") as f:
            f.write(bytes(md))
        paths.append(p)

    out = np.zeros_like(logical)
    for s in plan_stripe_reads(0, len(logical), n, chunk):
        with open(paths[s.member], "rb") as f:
            f.seek(s.member_offset)
            out[s.logical_offset:s.logical_offset + s.length] = \
                np.frombuffer(f.read(s.length), dtype=np.uint8)
    np.testing.assert_array_equal(out, logical)


def test_stripe_file_roundtrip(tmp_path, rng):
    """stripe_file writes the layout plan_stripe_reads decodes: striping a
    file then reading it back through StripedFile returns the original bytes
    (zero-padded tail past EOF)."""
    from strom.config import StromConfig
    from strom.delivery.core import StripedFile, StromContext
    from strom.engine.raid0 import stripe_file

    n, chunk = 4, 4096
    data = rng.integers(0, 256, size=n * chunk * 3 + 999, dtype=np.uint8)
    src = tmp_path / "src.bin"
    data.tofile(src)
    members = [str(tmp_path / f"sf{i}.bin") for i in range(n)]
    stripe_file(str(src), members, chunk)
    sf = StripedFile(tuple(members), chunk)
    assert sf.size >= len(data)
    ctx = StromContext(StromConfig(engine="python", queue_depth=8, num_buffers=8))
    try:
        got = np.asarray(ctx.memcpy_ssd2tpu(sf, length=sf.size))
    finally:
        ctx.close()
    np.testing.assert_array_equal(got[:len(data)], data)
    assert not got[len(data):].any()


def test_sidecar_size_sanity_and_cache(tmp_path, rng):
    """A stale size sidecar (members re-striped underneath it) claiming more
    bytes than the members can hold is distrusted: size falls back to the
    computed padded capacity. And the lookup is cached — rewriting the
    sidecar after the first .size access does not shift the perceived EOF
    mid-run."""
    from strom.delivery.core import StripedFile
    from strom.engine.raid0 import SIZE_SIDECAR_SUFFIX, stripe_file

    n, chunk = 2, 4096
    data = rng.integers(0, 256, size=n * chunk * 2, dtype=np.uint8)
    src = tmp_path / "src.bin"
    data.tofile(src)
    members = [str(tmp_path / f"sc{i}.bin") for i in range(n)]
    stripe_file(str(src), members, chunk)
    capacity = sum(os.path.getsize(m) for m in members)

    # stale sidecar claims 10x the capacity → distrusted, capacity wins
    with open(members[0] + SIZE_SIDECAR_SUFFIX, "w") as f:
        f.write(str(capacity * 10))
    sf = StripedFile(tuple(members), chunk)
    assert sf.size == capacity

    # honest sidecar is honored...
    with open(members[0] + SIZE_SIDECAR_SUFFIX, "w") as f:
        f.write(str(len(data)))
    sf2 = StripedFile(tuple(members), chunk)
    assert sf2.size == len(data)
    # ...and cached: a later rewrite cannot shift the EOF mid-run
    with open(members[0] + SIZE_SIDECAR_SUFFIX, "w") as f:
        f.write(str(chunk))
    assert sf2.size == len(data)
