"""Residency-aware hybrid read path (SURVEY.md §0.5 mechanism #5, §2.1
"Page-cache fallback"; reference cite UNVERIFIED — empty mount, SURVEY.md §0).

Cache-WARM ranges of a gather are served through the buffered fd (a memcpy
from the page cache) instead of being re-read from media O_DIRECT; cold
ranges are unchanged. The cached_bytes / media_bytes engine counters prove
which path every byte took.
"""

import os

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.probe.residency import cached_pages, drop_cache, range_fully_cached


def _probe_works(tmp_path) -> bool:
    p = tmp_path / "probe.bin"
    p.write_bytes(b"x" * 8192)
    fd = os.open(str(p), os.O_RDONLY)
    try:
        return cached_pages(fd, 0, 8192) is not None
    finally:
        os.close(fd)


@pytest.fixture()
def warmable_file(tmp_path, rng):
    """An 8MiB file plus a probe-availability gate (cachestat or mincore)."""
    if not _probe_works(tmp_path):
        pytest.skip("no residency probe on this kernel (cachestat+mincore)")
    data = rng.integers(0, 256, size=8 * 1024 * 1024, dtype=np.uint8)
    p = tmp_path / "warm.bin"
    data.tofile(p)
    return str(p), data


def test_probe_warm_cold_partial(warmable_file):
    path, data = warmable_file
    n = len(data)
    fd = os.open(path, os.O_RDONLY)
    try:
        # just written: dirty pages are resident
        assert range_fully_cached(fd, 0, n) is True
        drop_cache(path)
        res, tot = cached_pages(fd, 0, n)
        assert res == 0 and tot == n // 4096
        # exactly-half-warm, deterministically: warm everything, then evict
        # the tail (warming "half" by reading half is readahead-hostage — a
        # single 4MiB buffered read warms this box's whole file)
        with open(path, "rb") as f:
            f.read()
        os.posix_fadvise(fd, n // 2, n // 2, os.POSIX_FADV_DONTNEED)
        assert range_fully_cached(fd, 0, n // 2) is True
        assert range_fully_cached(fd, n - 4096, 4096) is False
        res, tot = cached_pages(fd, 0, n)
        assert res == n // 2 // 4096 and tot == n // 4096
        # probing must not populate: the tail stays cold after all the above
        assert range_fully_cached(fd, n - 4096, 4096) is False
    finally:
        os.close(fd)


def test_mincore_fallback_agrees(warmable_file, monkeypatch):
    """Force the mincore arm (dead code on cachestat-capable kernels) and
    check it reports the same warm/cold picture as the primary probe."""
    import strom.probe.residency as res_mod

    path, data = warmable_file
    n = len(data)
    fd = os.open(path, os.O_RDONLY)
    try:
        drop_cache(path)
        with open(path, "rb") as f:
            f.read()
        os.posix_fadvise(fd, n // 2, n // 2, os.POSIX_FADV_DONTNEED)
        primary = cached_pages(fd, 0, n)
        monkeypatch.setattr(res_mod, "_probe_state", 2)
        fallback = cached_pages(fd, 0, n)
        assert fallback is not None, "mincore fallback unprobeable"
        assert fallback == primary
        assert range_fully_cached(fd, 0, n // 2) is True
        assert range_fully_cached(fd, n - 4096, 4096) is False
    finally:
        os.close(fd)


def _counters(ctx) -> tuple[int, int]:
    s = ctx.engine.stats()
    return int(s.get("cached_bytes", 0)), int(s.get("media_bytes", 0))


@pytest.mark.parametrize("engine", ["python", "uring"])
def test_hybrid_counters_and_integrity(warmable_file, engine):
    """Cold file → all bytes from media; warmed file → all bytes from cache;
    identical bytes either way."""
    if engine == "uring":
        from strom.engine.uring_engine import uring_available

        if not uring_available():
            pytest.skip("io_uring unavailable")
    path, data = warmable_file
    n = len(data)
    ctx = StromContext(StromConfig(engine=engine))
    try:
        if not ctx.engine.file_uses_o_direct(ctx.file_index(path)):
            pytest.skip("O_DIRECT unavailable here: hybrid is moot")
        drop_cache(path)
        cold = bytes(memoryview(ctx.pread(path)))
        c1, m1 = _counters(ctx)
        assert cold == data.tobytes()
        assert c1 == 0 and m1 == n, (c1, m1)

        with open(path, "rb") as f:  # warm the whole file
            f.read()
        warm = bytes(memoryview(ctx.pread(path)))
        c2, m2 = _counters(ctx)
        assert warm == data.tobytes()
        assert c2 - c1 == n and m2 == m1, (c2 - c1, m2 - m1)
    finally:
        ctx.close()


@pytest.mark.parametrize("engine", ["python", "uring"])
def test_hybrid_partial_warm_splits(warmable_file, engine):
    """A half-warm file splits the gather: warm chunks ride the cache, cold
    chunks ride media, and the counters account for every byte."""
    if engine == "uring":
        from strom.engine.uring_engine import uring_available

        if not uring_available():
            pytest.skip("io_uring unavailable")
    path, data = warmable_file
    n = len(data)
    ctx = StromContext(StromConfig(engine=engine))
    try:
        if not ctx.engine.file_uses_o_direct(ctx.file_index(path)):
            pytest.skip("O_DIRECT unavailable here: hybrid is moot")
        # exactly-half-warm: sync+drop (dirty pages are unevictable), warm
        # everything clean, then evict the tail (reading just the first half
        # would readahead-warm the rest on this box)
        drop_cache(path)
        with open(path, "rb") as f:
            f.read()
        fd = os.open(path, os.O_RDONLY)
        os.posix_fadvise(fd, n // 2, n // 2, os.POSIX_FADV_DONTNEED)
        os.close(fd)
        got = bytes(memoryview(ctx.pread(path)))
        c, m = _counters(ctx)
        assert got == data.tobytes()
        assert c + m == n, (c, m)
        assert c == n // 2, (c, m)
        assert m == n // 2, (c, m)
    finally:
        ctx.close()


def test_hybrid_off_reads_media(warmable_file):
    """residency_hybrid=False: a fully-warm file is still read O_DIRECT
    (cold-path behavior preserved, counters prove it)."""
    from strom.engine.uring_engine import uring_available

    if not uring_available():
        pytest.skip("io_uring unavailable")
    path, data = warmable_file
    ctx = StromContext(StromConfig(engine="uring", residency_hybrid=False))
    try:
        if not ctx.engine.file_uses_o_direct(ctx.file_index(path)):
            pytest.skip("O_DIRECT unavailable here: hybrid is moot")
        with open(path, "rb") as f:
            f.read()
        got = bytes(memoryview(ctx.pread(path)))
        c, m = _counters(ctx)
        assert got == data.tobytes()
        assert c == 0 and m == len(data), (c, m)
    finally:
        ctx.close()


def test_hybrid_striped_set(tmp_path, rng):
    """RAID0 gathers ride the hybrid per member: warming the members routes
    the striped read through the cache."""
    if not _probe_works(tmp_path):
        pytest.skip("no residency probe on this kernel")
    from strom.delivery.core import StripedFile
    from strom.engine.raid0 import stripe_file

    n_mem, chunk = 2, 64 * 1024
    data = rng.integers(0, 256, size=4 * 1024 * 1024, dtype=np.uint8)
    src = tmp_path / "src.bin"
    data.tofile(src)
    members = [str(tmp_path / f"m{i}.bin") for i in range(n_mem)]
    stripe_file(str(src), members, chunk)
    sf = StripedFile(tuple(members), chunk)
    ctx = StromContext(StromConfig(engine="uring"))
    try:
        from strom.engine.uring_engine import uring_available

        if not uring_available():
            pytest.skip("io_uring unavailable")
        if not ctx.engine.file_uses_o_direct(ctx.file_index(members[0])):
            pytest.skip("O_DIRECT unavailable here")
        for m in members:
            with open(m, "rb") as f:
                f.read()
        got = np.asarray(ctx.memcpy_ssd2tpu(sf, length=len(data)))
        c, _ = _counters(ctx)
        np.testing.assert_array_equal(got, data)
        assert c == len(data), c
    finally:
        ctx.close()


@pytest.mark.parametrize("engine", ["python", "uring"])
def test_mixed_probe_count_bounded(warmable_file, engine):
    """A mixed (half-warm) segment spanning MANY block_size chunks probes
    residency in bounded groups — <= 1 + 256 probe syscalls per segment
    however many chunks it has (VERDICT.md r3 weak #5) — with the byte
    accounting and integrity unchanged. block_size=4096 makes the 8MiB
    fixture span 2048 chunks, which unbounded per-chunk probing would have
    hit with 2049 probes."""
    if engine == "uring":
        from strom.engine.uring_engine import uring_available

        if not uring_available():
            pytest.skip("io_uring unavailable")
    path, data = warmable_file
    n = len(data)
    ctx = StromContext(StromConfig(engine=engine, block_size=4096))
    try:
        if not ctx.engine.file_uses_o_direct(ctx.file_index(path)):
            pytest.skip("O_DIRECT unavailable here: hybrid is moot")
        drop_cache(path)
        with open(path, "rb") as f:
            f.read()
        fd = os.open(path, os.O_RDONLY)
        os.posix_fadvise(fd, n // 2, n // 2, os.POSIX_FADV_DONTNEED)
        os.close(fd)
        p0 = int(ctx.engine.stats().get("residency_probes", 0))
        got = bytes(memoryview(ctx.pread(path)))
        s = ctx.engine.stats()
        probes = int(s.get("residency_probes", 0)) - p0
        assert got == data.tobytes()
        # 1 whole-segment probe + at most 256 group probes; no lazy worker
        # probes (every piece got an upfront verdict)
        assert 0 < probes <= 257, probes
        c, m = _counters(ctx)
        assert c + m == n, (c, m)
        # the group size (2048/256 = 8 chunks = 32KiB) divides the 4MiB warm
        # half exactly, so the split stays byte-exact even probed coarsely
        assert c == n // 2 and m == n // 2, (c, m)
    finally:
        ctx.close()
