"""Test env: force the jax CPU backend with a fake 8-device mesh BEFORE any
jax import (SURVEY.md §4.2 "Device delivery" row)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sandbox pins JAX_PLATFORMS=axon at interpreter startup; the config
# update (before any backend is touched) wins over it.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# the dryrun's 16/32-device lowering runs in a subprocess (own jax
# cold-start + an 8B pp lowering) — driver-artifact work, not suite work
# on a 1-core box; the dryrun test covers the executed 8-device matrix
os.environ.setdefault("STROM_DRYRUN_AT_SCALE", "0")
# same policy for the dryrun's measured 2-process dist ingest (ISSUE 15):
# tests/test_dist.py drives the data plane directly
os.environ.setdefault("STROM_DRYRUN_DIST", "0")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)


@pytest.fixture()
def data_file(tmp_path, rng):
    """A 4MiB+tail random file on real disk (tmp_path is on ext4 here, so
    O_DIRECT works; SURVEY.md §4.2 'Engine integration' row)."""
    data = rng.integers(0, 256, size=4 * 1024 * 1024 + 777, dtype=np.uint8)
    p = tmp_path / "data.bin"
    data.tofile(p)
    return str(p), data


@pytest.fixture(params=["python", "uring"])
def engine_name(request):
    if request.param == "uring":
        from strom.engine.uring_engine import uring_available

        if not uring_available():
            pytest.skip("io_uring unavailable in this sandbox")
    return request.param
