"""Slab recycling (SURVEY.md §7.4 hard parts #1/#3): page-fault amortization
with a strict release-after-transfer lifetime contract."""

import numpy as np

from strom.delivery.buffers import PAGE, SlabPool, alloc_aligned, size_class


class TestSizeClass:
    def test_basic_properties(self):
        for n in (1, 100, PAGE, PAGE + 1, 128 << 10, (128 << 10) + 7,
                  1 << 20, (1 << 20) + 1, 777_777_777):
            c = size_class(n)
            assert c >= max(n, PAGE)          # never smaller than the request
            assert c % PAGE == 0              # always a page multiple
            if n >= 4 * PAGE:
                assert c <= n * 1.25          # <= 25% internal waste
            else:
                assert c <= 2 * max(n, PAGE)  # tiny sizes: page-pow2 steps

    def test_pow2_is_identity(self):
        for shift in (12, 17, 20, 27, 30):
            assert size_class(1 << shift) == 1 << shift

    def test_quantizes_nearby_sizes(self):
        # sizes within a quarter-step collapse to one class → recycling works
        assert size_class((1 << 20) + 1) == size_class((1 << 20) + (1 << 18))


class TestSlabPool:
    def test_mixed_sizes_recycle(self):
        """VERDICT.md weak #7: exact-match buckets degenerate to 100% misses
        on mixed sizes; size classes must keep the hit rate high."""
        rng = np.random.default_rng(0)
        pool = SlabPool(max_bytes=1 << 30)
        # variable batch geometry: sizes jitter ±12% around a few bases
        bases = [256 << 10, 1 << 20, 3 << 20]
        for _ in range(200):
            base = bases[rng.integers(len(bases))]
            n = int(base * (1 + rng.uniform(-0.12, 0.12)))
            s = pool.acquire(n)
            assert s.nbytes == n
            pool.release(s)
        st = pool.stats()
        hit_rate = st["hits"] / (st["hits"] + st["misses"])
        assert hit_rate > 0.9, st

    def test_view_release_returns_full_class(self):
        pool = SlabPool(max_bytes=1 << 30)
        a = pool.acquire(5000)  # class 8192
        assert a.nbytes == 5000
        pool.release(a)
        st = pool.stats()
        assert st["cached_bytes"] == size_class(5000)
        b = pool.acquire(6000)  # same class → recycled
        assert b.nbytes == 6000 and pool.hits == 1

    def test_huge_pages_recycle_and_fallback(self):
        """huge=True: bucket key must equal the mmap length whichever page
        size actually backed the slab (reserved hugepages OR the silent
        4KiB fallback), so recycling keeps working either way."""
        from strom.delivery.buffers import HUGE_PAGE

        pool = SlabPool(max_bytes=1 << 30, huge=True)
        a = pool.acquire(3 << 20)  # class rounds up to 4MiB
        assert a.nbytes == 3 << 20
        pool.release(a)
        st = pool.stats()
        assert st["huge"] is True
        (cls,) = st["buckets"].keys()
        assert cls % HUGE_PAGE == 0
        b = pool.acquire(4 << 20)  # same 4MiB class -> recycled
        assert pool.hits == 1 and b.nbytes == 4 << 20

    def test_huge_alloc_oversubscribed_falls_back(self):
        # more than THIS box's actual reservation (read, not guessed): the
        # hugetlb mmap must fail with ENOMEM and silently fall back to
        # normal pages, not raise or SIGBUS
        from strom.delivery.buffers import HUGE_PAGE

        total = 0
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("HugePages_Total"):
                    total = int(line.split()[1])
        arr = alloc_aligned((total + 8) * HUGE_PAGE, huge=True)
        arr[:100] = 5
        assert (arr[:100] == 5).all()

    def test_mlock_cap(self):
        pool = SlabPool(max_bytes=1 << 30, pin=True, max_mlock_bytes=64 << 10)
        slabs = [pool.acquire(32 << 10) for _ in range(4)]
        st = pool.stats()
        # best-effort: never exceeds the cap (may be 0 if RLIMIT_MEMLOCK tiny)
        assert st["mlocked_bytes"] <= 64 << 10
        assert st["mlock_cap_bytes"] == 64 << 10
        for s in slabs:
            pool.release(s)
        assert pool.stats()["mlocked_bytes"] <= 64 << 10
    def test_acquire_release_recycles(self):
        pool = SlabPool(max_bytes=1 << 20)
        a = pool.acquire(4096)
        addr = a.__array_interface__["data"][0]
        pool.release(a)
        b = pool.acquire(4096)
        assert b.__array_interface__["data"][0] == addr
        assert pool.hits == 1 and pool.misses == 1

    def test_size_buckets_dont_mix(self):
        pool = SlabPool(max_bytes=1 << 20)
        a = pool.acquire(4096)
        pool.release(a)
        c = pool.acquire(8192)
        assert c.nbytes == 8192
        assert pool.stats()["buckets"] == {4096: 1}

    def test_cap_drops_excess(self):
        pool = SlabPool(max_bytes=8192)
        slabs = [pool.acquire(4096) for _ in range(3)]
        for s in slabs:
            pool.release(s)
        assert pool.stats()["cached_bytes"] <= 8192

    def test_alignment_and_populate(self):
        a = alloc_aligned(10_000, populate=True)
        assert a.__array_interface__["data"][0] % 4096 == 0
        a[:] = 3  # writable
        p = SlabPool()
        b = p.acquire(10_000)
        assert b.__array_interface__["data"][0] % 4096 == 0

    def test_cpu_backend_bypasses_pool(self, data_file):
        """On the jax CPU backend device_put aliases host memory, so the
        delivery path must NOT recycle (content would be corrupted)."""
        import jax

        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        path, golden = data_file
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8))
        try:
            a1 = ctx.memcpy_ssd2tpu(path, length=1 << 20,
                                    device=jax.devices()[0])
            a2 = ctx.memcpy_ssd2tpu(path, offset=1 << 20, length=1 << 20,
                                    device=jax.devices()[0])
            # both must stay correct — a recycle would have overwritten a1
            np.testing.assert_array_equal(np.asarray(a1), golden[: 1 << 20])
            np.testing.assert_array_equal(np.asarray(a2),
                                          golden[1 << 20: 2 << 20])
            assert ctx._slab_pool is not None
            assert ctx._slab_pool.hits == 0  # pool never engaged on cpu
        finally:
            ctx.close()
