"""Slab recycling (SURVEY.md §7.4 hard parts #1/#3): page-fault amortization
with a strict release-after-transfer lifetime contract."""

import numpy as np

from strom.delivery.buffers import SlabPool, alloc_aligned


class TestSlabPool:
    def test_acquire_release_recycles(self):
        pool = SlabPool(max_bytes=1 << 20)
        a = pool.acquire(4096)
        addr = a.__array_interface__["data"][0]
        pool.release(a)
        b = pool.acquire(4096)
        assert b.__array_interface__["data"][0] == addr
        assert pool.hits == 1 and pool.misses == 1

    def test_size_buckets_dont_mix(self):
        pool = SlabPool(max_bytes=1 << 20)
        a = pool.acquire(4096)
        pool.release(a)
        c = pool.acquire(8192)
        assert c.nbytes == 8192
        assert pool.stats()["buckets"] == {4096: 1}

    def test_cap_drops_excess(self):
        pool = SlabPool(max_bytes=8192)
        slabs = [pool.acquire(4096) for _ in range(3)]
        for s in slabs:
            pool.release(s)
        assert pool.stats()["cached_bytes"] <= 8192

    def test_alignment_and_populate(self):
        a = alloc_aligned(10_000, populate=True)
        assert a.__array_interface__["data"][0] % 4096 == 0
        a[:] = 3  # writable
        p = SlabPool()
        b = p.acquire(10_000)
        assert b.__array_interface__["data"][0] % 4096 == 0

    def test_cpu_backend_bypasses_pool(self, data_file):
        """On the jax CPU backend device_put aliases host memory, so the
        delivery path must NOT recycle (content would be corrupted)."""
        import jax

        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        path, golden = data_file
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8))
        try:
            a1 = ctx.memcpy_ssd2tpu(path, length=1 << 20,
                                    device=jax.devices()[0])
            a2 = ctx.memcpy_ssd2tpu(path, offset=1 << 20, length=1 << 20,
                                    device=jax.devices()[0])
            # both must stay correct — a recycle would have overwritten a1
            np.testing.assert_array_equal(np.asarray(a1), golden[: 1 << 20])
            np.testing.assert_array_equal(np.asarray(a2),
                                          golden[1 << 20: 2 << 20])
            assert ctx._slab_pool is not None
            assert ctx._slab_pool.hits == 0  # pool never engaged on cpu
        finally:
            ctx.close()
