"""Delivery-scheduler unit tests: segment/op coalescing (adjacency, overlap,
split threshold, RAID0 boundaries) and the striped overlap-window submission
order (byte-mapping invariance, per-member grouping, error propagation)."""

import os

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.coalesce import coalesce_chunks, coalesce_segments
from strom.delivery.core import StromContext
from strom.delivery.shard import Segment
from strom.engine.base import EngineError
from strom.engine.raid0 import (plan_stripe_reads, plan_stripe_windows,
                                stripe_file)


def cover_map(segs):
    """{dest_byte: file_byte} a segment list describes — the invariant every
    scheduler transform must preserve."""
    m = {}
    for s in segs:
        for i in range(s.length):
            m[s.dest_offset + i] = s.file_offset + i
    return m


def chunk_cover_map(chunks):
    m = {}
    for fi, fo, do, ln in chunks:
        for i in range(ln):
            m[do + i] = (fi, fo + i)
    return m


class TestCoalesceSegments:
    def test_adjacent_merge(self):
        segs = [Segment(0, 0, 100), Segment(100, 100, 50),
                Segment(150, 150, 50)]
        out = coalesce_segments(segs)
        assert out == [Segment(0, 0, 200)]

    def test_gap_not_merged(self):
        segs = [Segment(0, 0, 100), Segment(200, 100, 50)]
        assert coalesce_segments(segs) == segs

    def test_adjacent_file_but_not_dest(self):
        # file-contiguous but dest-disjoint (different deltas): two copies
        segs = [Segment(0, 0, 100), Segment(100, 500, 100)]
        assert sorted(coalesce_segments(segs),
                      key=lambda s: s.dest_offset) == segs

    def test_overlap_same_delta_dedupes_to_union(self):
        segs = [Segment(0, 0, 100), Segment(50, 50, 100)]
        out = coalesce_segments(segs)
        assert out == [Segment(0, 0, 150)]
        assert cover_map(out) == cover_map(segs)

    def test_out_of_order_input(self):
        segs = [Segment(150, 150, 50), Segment(0, 0, 100),
                Segment(100, 100, 50)]
        assert coalesce_segments(segs) == [Segment(0, 0, 200)]

    def test_split_threshold(self):
        segs = [Segment(0, 0, 100), Segment(100, 100, 100)]
        out = coalesce_segments(segs, max_bytes=64)
        assert all(s.length <= 64 for s in out)
        assert cover_map(out) == cover_map(segs)

    def test_cover_map_preserved(self):
        rng = np.random.default_rng(7)
        segs = []
        dest = 0
        fo = 0
        for _ in range(40):
            ln = int(rng.integers(1, 2000))
            fo += int(rng.integers(0, 2)) * int(rng.integers(0, 500))
            segs.append(Segment(fo, dest, ln))
            fo += ln
            dest += ln
        out = coalesce_segments(segs, max_bytes=4096)
        assert cover_map(out) == cover_map(segs)
        assert len(out) <= len(segs) + sum(s.length for s in segs) // 4096 + 1


class TestCoalesceChunks:
    def test_merge_within_file_only(self):
        ch = [(1, 0, 0, 100), (1, 100, 100, 100), (2, 200, 200, 100),
              (2, 300, 300, 100)]
        out = coalesce_chunks(ch)
        assert out == [(1, 0, 0, 200), (2, 200, 200, 200)]

    def test_interleaved_files_regroup(self):
        # a WDS-style interleave: per-sample fragments alternating files
        ch = [(1, 0, 0, 10), (2, 0, 10, 10), (1, 10, 20, 10), (2, 10, 30, 10)]
        out = coalesce_chunks(ch)
        # nothing merges (file runs are dest-discontiguous) but the mapping
        # survives and files keep first-appearance order
        assert chunk_cover_map(out) == chunk_cover_map(ch)
        assert [c[0] for c in out] == [1, 1, 2, 2]

    def test_split_threshold(self):
        ch = [(1, 0, 0, 1000), (1, 1000, 1000, 1000)]
        out = coalesce_chunks(ch, max_bytes=512)
        assert all(c[3] <= 512 for c in out)
        assert chunk_cover_map(out) == chunk_cover_map(ch)

    def test_raid0_member_chunks_never_cross_members(self):
        """Chunks expanded from a stripe plan: member ops stay per-member
        and (dest-discontiguous by construction) never merge across chunk
        boundaries — coalescing must not corrupt the stripe decode."""
        segs = plan_stripe_reads(0, 4 << 20, 4, 512 * 1024)
        ch = [(s.member, s.member_offset, s.logical_offset, s.length)
              for s in segs]
        out = coalesce_chunks(ch)
        assert chunk_cover_map(out) == chunk_cover_map(ch)
        # every output op maps entirely inside one member
        assert {c[0] for c in out} == {0, 1, 2, 3}

    def test_single_member_stripe_merges_fully(self):
        # n=1 "striping" is plain contiguity: one op after coalescing
        segs = plan_stripe_reads(0, 1 << 20, 1, 128 * 1024)
        ch = [(0, s.member_offset, s.logical_offset, s.length) for s in segs]
        assert coalesce_chunks(ch) == [(0, 0, 0, 1 << 20)]


class TestStripeWindows:
    def test_same_byte_mapping(self):
        segs = plan_stripe_reads(12345, 9 << 20, 4, 512 * 1024)
        out = plan_stripe_windows(segs, 4, 4 << 20)
        key = lambda s: (s.member, s.member_offset, s.logical_offset, s.length)
        assert sorted(map(key, out)) == sorted(map(key, segs))

    def test_groups_per_member_within_window(self):
        segs = plan_stripe_reads(0, 8 << 20, 4, 512 * 1024)
        out = plan_stripe_windows(segs, 4, 4 << 20)
        # first window = 8 segs: members grouped 0,0,1,1,2,2,3,3
        first = [s.member for s in out[:8]]
        assert first == [0, 0, 1, 1, 2, 2, 3, 3]
        # within a member's run, member offsets are sequential
        runs = [out[0:2], out[2:4], out[4:6], out[6:8]]
        for run in runs:
            assert run[1].member_offset == run[0].member_offset + run[0].length

    def test_window_zero_keeps_logical_order(self):
        segs = plan_stripe_reads(0, 4 << 20, 4, 512 * 1024)
        assert plan_stripe_windows(segs, 4, 0) == list(segs)

    def test_tail_window_flushes(self):
        segs = plan_stripe_reads(0, (4 << 20) + (3 * 512 * 1024), 4,
                                 512 * 1024)
        out = plan_stripe_windows(segs, 4, 4 << 20)
        assert len(out) == len(segs)

    def test_count_matches_flushes(self):
        from strom.engine.raid0 import count_stripe_windows

        # lengths that don't divide the window: a flush consumes MORE than
        # window_bytes, so ceil(total/wb) would overcount — the counter
        # must match the actual flush rule
        for total, chunk, wb in ((10 << 20, 3 << 20, 4 << 20),
                                 ((4 << 20) + (3 * 512 * 1024), 512 * 1024,
                                  4 << 20),
                                 (9 << 20, 512 * 1024, 4 << 20)):
            segs = plan_stripe_reads(0, total, 4, chunk)
            n = count_stripe_windows(segs, 4, wb)
            # replicate by instrumenting: group boundaries in the planned
            # output are where the member id resets to the minimum member
            # of a fresh window — instead, just recompute flushes directly
            acc, flushes = 0, 0
            for s in segs:
                acc += s.length
                if acc >= wb:
                    flushes += 1
                    acc = 0
            assert n == flushes + (1 if acc else 0)
        assert count_stripe_windows(segs, 1, 4 << 20) == 0  # n=1: no-op
        assert count_stripe_windows(segs, 4, 0) == 0        # off: no-op


@pytest.fixture()
def striped_set(tmp_path, rng):
    data = rng.integers(0, 256, 6 * 1024 * 1024 + 333, dtype=np.uint8)
    src = tmp_path / "src.bin"
    data.tofile(src)
    members = [str(tmp_path / f"m{i}") for i in range(4)]
    stripe_file(str(src), members, 256 * 1024)
    return members, data


class TestStripedDelivery:
    """The windowed submission order through the real delivery path: bytes
    identical to logical order, completions order-independent, errors
    propagate."""

    def _ctx(self, **kw):
        return StromContext(StromConfig(engine="python", **kw))

    def test_windowed_read_matches_data(self, tmp_path, striped_set):
        members, data = striped_set
        ctx = self._ctx()
        try:
            ctx.register_striped(str(tmp_path / "virt"), members, 256 * 1024)
            out = ctx.memcpy_ssd2host(str(tmp_path / "virt"),
                                      length=len(data))
            np.testing.assert_array_equal(out.reshape(-1), data)
            snap = ctx.stats()["context"]
            assert snap["stripe_windows"] > 0
            assert snap["stripe_overlap_window_bytes"] > 0
        finally:
            ctx.close()

    def test_window_off_matches_window_on(self, tmp_path, striped_set):
        members, data = striped_set
        for wb in (0, 1 << 20, 16 << 20):
            ctx = self._ctx(stripe_window_bytes=wb)
            try:
                ctx.register_striped(str(tmp_path / "virt"), members,
                                     256 * 1024)
                out = ctx.memcpy_ssd2host(str(tmp_path / "virt"),
                                          length=len(data))
                np.testing.assert_array_equal(out.reshape(-1), data)
            finally:
                ctx.close()

    def test_offset_reads_identical(self, tmp_path, striped_set):
        members, data = striped_set
        ctx = self._ctx()
        try:
            ctx.register_striped(str(tmp_path / "virt"), members, 256 * 1024)
            for off, ln in ((0, 700_000), (513 * 1024, 2 << 20),
                            (1_000_001, 999_999)):
                out = ctx.pread(str(tmp_path / "virt"), off, ln)
                np.testing.assert_array_equal(out, data[off: off + ln])
        finally:
            ctx.close()

    def test_error_mid_pipeline_propagates(self, tmp_path, striped_set):
        """A member truncated mid-set: the windowed gather must surface
        EngineError (short read), not return silently-zeroed bytes."""
        members, data = striped_set
        # remove the size sidecar so StripedFile.size reports full stripe
        # capacity, then truncate one member mid-file
        os.unlink(members[0] + ".stromsz")
        with open(members[2], "r+b") as f:
            f.truncate(os.path.getsize(members[2]) // 2)
        ctx = self._ctx()
        try:
            ctx.register_striped(str(tmp_path / "virt"), members, 256 * 1024,
                                 size=len(data))
            with pytest.raises(EngineError):
                ctx.memcpy_ssd2host(str(tmp_path / "virt"), length=len(data))
        finally:
            ctx.close()
