"""Flight recorder (ISSUE 6 tentpole): watchdog, triggers, atomic bundles.

The invariants: a slow-but-ADVANCING run never trips the stall trigger
(progress is counter deltas, not wall-per-step); a genuinely wedged run
dumps exactly one bundle per stall episode; every dumped bundle is atomic
and round-trips through ``load_bundle``; SIGTERM/excepthook dumps chain
the previous handlers.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from strom.obs import flight
from strom.obs.events import EventRing
from strom.obs.flight import (FLIGHT_FIELDS, FlightRecorder, capture_doc,
                              load_bundle, thread_stacks)


def mk_recorder(tmp_path, **kw):
    kw.setdefault("install_signal", False)
    kw.setdefault("install_excepthook", False)
    kw.setdefault("interval_s", 0.05)
    return FlightRecorder(str(tmp_path / "fl"), **kw)


def bundles(tmp_path):
    return sorted(glob.glob(str(tmp_path / "fl" / "flight-*")))


class TestWatchdog:
    def test_samples_accumulate_with_flight_fields(self, tmp_path):
        with mk_recorder(tmp_path) as rec:
            time.sleep(0.3)
            samples = rec.samples()
        assert samples
        assert set(samples[0]) == set(FLIGHT_FIELDS)

    def test_sample_ring_bounded(self, tmp_path):
        with mk_recorder(tmp_path, max_samples=8, interval_s=0.01) as rec:
            time.sleep(0.5)
            assert len(rec.samples()) <= 8

    def test_no_false_positive_while_progressing(self, tmp_path):
        """A deliberately slow step loop — progress every ~0.15s against a
        0.3s stall threshold — must never dump: any delta resets the
        stall clock."""
        val = [0]
        with mk_recorder(tmp_path, stall_s=0.3,
                         progress_fn=lambda: val[0]) as rec:
            for _ in range(8):  # ~1.2s of slow-but-advancing stepping
                time.sleep(0.15)
                val[0] += 1
            assert rec._dumps == 0
        assert bundles(tmp_path) == []

    def test_stall_dumps_once_per_episode(self, tmp_path):
        val = [0]
        with mk_recorder(tmp_path, stall_s=0.2,
                         progress_fn=lambda: val[0]) as rec:
            deadline = time.monotonic() + 5.0
            while rec._dumps == 0 and time.monotonic() < deadline:
                time.sleep(0.05)  # no progress: the watchdog should fire
            assert rec._dumps == 1
            time.sleep(0.5)  # STILL no progress: same episode, no re-dump
            assert rec._dumps == 1
            val[0] += 1  # recovery...
            time.sleep(0.3)
            while time.monotonic() < deadline and rec._dumps < 2:
                time.sleep(0.05)  # ...then a second stall episode
            assert rec._dumps == 2
        bs = bundles(tmp_path)
        assert len(bs) == 2
        assert all("stall" in b for b in bs)

    def test_stall_disabled_at_zero(self, tmp_path):
        with mk_recorder(tmp_path, stall_s=0.0,
                         progress_fn=lambda: 7) as rec:
            time.sleep(0.4)
            assert rec._dumps == 0


class TestBundle:
    def test_dump_round_trip(self, tmp_path):
        # seed the global exemplar store: a crash bundle must carry the
        # tail-sampled span trees of the slowest recent requests (ISSUE 8)
        from strom.obs.exemplars import store
        from strom.obs.request import Request

        store.clear()
        req = Request("gather", "flight-t0")
        req.note_queue_wait(123.0, throttled=True)
        req.finish()
        ring = EventRing(capacity=64)
        ring.complete(0.0, 5.0, "read", "t.read", {"bytes": 3})
        with mk_recorder(tmp_path, ring=ring) as rec:
            p = rec.dump("test", note="hello")
        b = load_bundle(p)
        assert b["manifest"]["reason"] == "test"
        assert b["manifest"]["note"] == "hello"
        assert b["manifest"]["fields"] == list(FLIGHT_FIELDS)
        assert b["manifest"]["samples"]  # at least the capture-time sample
        assert any(ev.get("name") == "t.read"
                   for ev in b["trace"]["traceEvents"])
        assert "global" in b["stats"] and "scopes" in b["stats"]
        assert "thread" in b["stacks"]
        # exemplars member round-trips, throttled request tree included,
        # and the watchdog samples carry the retention counter
        exs = b["exemplars"]["tenants"]["flight-t0"]
        assert any(e["req"] == req.id and e["throttled"] for e in exs)
        assert b["exemplars"]["exemplars_retained"] >= 1
        assert all("exemplars_retained" in s
                   for s in b["manifest"]["samples"])
        store.clear()

    def test_dump_atomic_no_tmp_left(self, tmp_path):
        with mk_recorder(tmp_path) as rec:
            rec.dump("a")
            rec.dump("a")
        assert not glob.glob(str(tmp_path / "fl" / ".tmp-*"))
        assert len(bundles(tmp_path)) == 2  # serials keep them apart

    def test_capture_doc_without_recorder(self):
        doc = capture_doc()
        assert doc["reason"] == "on_demand"
        assert "stacks" in doc and "trace" in doc

    def test_thread_stacks_sees_this_test(self):
        assert "test_thread_stacks_sees_this_test" in thread_stacks()


class TestTriggers:
    def test_sigterm_dumps_and_chains(self, tmp_path):
        """Child installs a prior SIGTERM handler (the bench.py emergency
        flush shape), then the recorder; a SIGTERM must dump the bundle
        AND still run the prior handler."""
        child = tmp_path / "child.py"
        fdir = tmp_path / "fl"
        child.write_text(f"""
import os, signal, sys, time
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})

def prev(signum, frame):
    os.write(1, b"PREV\\n")
    os._exit(0)

signal.signal(signal.SIGTERM, prev)
from strom.obs.flight import FlightRecorder
FlightRecorder({str(fdir)!r}, stall_s=0.0, interval_s=0.05)
os.write(1, b"READY\\n")
time.sleep(30)
""")
        p = subprocess.Popen([sys.executable, str(child)],
                             stdout=subprocess.PIPE)
        try:
            assert p.stdout.readline().strip() == b"READY"
            time.sleep(0.2)
            p.send_signal(signal.SIGTERM)
            out, _ = p.communicate(timeout=15)
        finally:
            if p.poll() is None:
                p.kill()
        assert b"PREV" in out
        assert p.returncode == 0  # the chained handler decided the exit
        bs = sorted(glob.glob(str(fdir / "flight-*")))
        assert len(bs) == 1
        assert load_bundle(bs[0])["manifest"]["reason"] == "sigterm"

    def test_sigterm_default_reraises(self, tmp_path):
        """Without a prior handler the process must still die BY SIGTERM
        (the driver's rc accounting keys off the wait status)."""
        child = tmp_path / "child.py"
        fdir = tmp_path / "fl"
        child.write_text(f"""
import os, sys, time
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from strom.obs.flight import FlightRecorder
FlightRecorder({str(fdir)!r}, stall_s=0.0, interval_s=0.05)
os.write(1, b"READY\\n")
time.sleep(30)
""")
        p = subprocess.Popen([sys.executable, str(child)],
                             stdout=subprocess.PIPE)
        try:
            assert p.stdout.readline().strip() == b"READY"
            time.sleep(0.2)
            p.send_signal(signal.SIGTERM)
            p.communicate(timeout=15)
        finally:
            if p.poll() is None:
                p.kill()
        assert p.returncode == -signal.SIGTERM
        assert glob.glob(str(fdir / "flight-*-sigterm-*"))

    def test_sigterm_sig_ign_stays_ignored(self, tmp_path):
        """A process that deliberately ignores SIGTERM must survive it
        with a recorder armed: dump the bundle, keep ignoring."""
        child = tmp_path / "child.py"
        fdir = tmp_path / "fl"
        child.write_text(f"""
import os, signal, sys, time
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
signal.signal(signal.SIGTERM, signal.SIG_IGN)
from strom.obs.flight import FlightRecorder
FlightRecorder({str(fdir)!r}, stall_s=0.0, interval_s=0.05)
os.write(1, b"READY\\n")
time.sleep(2)
os.write(1, b"SURVIVED\\n")
""")
        p = subprocess.Popen([sys.executable, str(child)],
                             stdout=subprocess.PIPE)
        try:
            assert p.stdout.readline().strip() == b"READY"
            time.sleep(0.2)
            p.send_signal(signal.SIGTERM)
            out, _ = p.communicate(timeout=15)
        finally:
            if p.poll() is None:
                p.kill()
        assert b"SURVIVED" in out and p.returncode == 0
        assert glob.glob(str(fdir / "flight-*-sigterm-*"))

    def test_close_does_not_clobber_chained_recorder(self, tmp_path):
        """Recorder A closes while recorder B (created later, chained on
        top) is still live: B's hooks must stay installed."""
        import sys as _sys

        prev_hook = _sys.excepthook
        a = mk_recorder(tmp_path / "a", install_excepthook=True)
        b = mk_recorder(tmp_path / "b", install_excepthook=True)
        try:
            assert _sys.excepthook is b._installed_excepthook
            a.close()
            # out-of-order close: B's hook survives
            assert _sys.excepthook is b._installed_excepthook
        finally:
            b.close()
        assert _sys.excepthook is not b._installed_excepthook
        # in-order teardown restored the chain all the way down:
        # B restored to A's hook; A's link is inert (already closed)
        # and the original hook is reachable through it
        assert _sys.excepthook is a._installed_excepthook
        _sys.excepthook = prev_hook

    def test_excepthook_dumps_and_chains(self, tmp_path):
        child = tmp_path / "child.py"
        fdir = tmp_path / "fl"
        child.write_text(f"""
import sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from strom.obs.flight import FlightRecorder
FlightRecorder({str(fdir)!r}, stall_s=0.0, install_signal=False)
raise RuntimeError("boom-42")
""")
        p = subprocess.run([sys.executable, str(child)],
                           capture_output=True, timeout=30)
        assert p.returncode == 1
        assert b"boom-42" in p.stderr  # the default hook still printed
        bs = glob.glob(str(fdir / "flight-*-exception-*"))
        assert len(bs) == 1
        m = load_bundle(bs[0])["manifest"]
        assert "boom-42" in m["note"]


class TestContextIntegration:
    def test_context_starts_and_closes_recorder(self, tmp_path):
        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        cfg = StromConfig(engine="python", slab_pool_bytes=0,
                          flight_dir=str(tmp_path / "fl"),
                          flight_stall_s=0.0)
        ctx = StromContext(cfg)
        try:
            rec = ctx.flight_recorder
            assert rec is not None
            p = rec.dump("ctx")
            b = load_bundle(p)
            # the context-backed capture includes the sections snapshot
            assert "sections" in b["stats"]
            assert "engine" in b["stats"]["sections"]
        finally:
            ctx.close()
        assert rec._closed.is_set()

    def test_flight_route_serves_capture(self, tmp_path):
        import urllib.request

        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        cfg = StromConfig(engine="python", slab_pool_bytes=0,
                          flight_dir=str(tmp_path / "fl"),
                          flight_stall_s=0.0)
        ctx = StromContext(cfg, metrics_port=0)
        try:
            port = ctx.metrics_server.port
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/flight").read())
            assert doc["reason"] == "on_demand"
            assert doc["fields"] == list(FLIGHT_FIELDS)
            assert "stacks" in doc
            doc2 = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/flight?dump=1").read())
            assert doc2["bundle_path"]
            assert load_bundle(doc2["bundle_path"])["manifest"]["reason"] \
                == "on_demand"
        finally:
            ctx.close()

    def test_flight_route_without_recorder(self):
        """/flight still captures (point-in-time) when no recorder is
        configured."""
        from strom.obs.server import MetricsServer

        srv = MetricsServer(None, port=0)
        try:
            import urllib.request

            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/flight").read())
            assert doc["reason"] == "on_demand"
            assert doc["samples"] == []
        finally:
            srv.close()
