"""Probe layer: FIEMAP, O_DIRECT probing, check_file tiers (SURVEY.md §4.2)."""

import os

import numpy as np
import pytest

from strom.probe import check_file, fiemap, probe_dio
from strom.probe.check import PathTier
from strom.probe.fiemap import coverage


def test_probe_dio_regular_file(data_file):
    path, _ = data_file
    dio = probe_dio(path)
    assert dio.supported in (True, False)
    if dio.supported:
        assert dio.mem_align > 0 and dio.offset_align > 0
        assert dio.mem_align % 512 == 0 or dio.mem_align in (1, 512)


def test_fiemap_covers_file(data_file):
    path, data = data_file
    try:
        ext = fiemap(path)
    except OSError:
        pytest.skip("fiemap unsupported on this filesystem")
    assert ext, "expected at least one extent"
    assert coverage([e for e in ext if e.is_reliable], len(data)) >= 0.99


def test_fiemap_on_sparse_file(tmp_path):
    p = tmp_path / "sparse.bin"
    with open(p, "wb") as f:
        f.seek(10 * 1024 * 1024 - 1)
        f.write(b"\x01")
    try:
        ext = fiemap(str(p))
    except OSError:
        pytest.skip("fiemap unsupported")
    total = sum(e.length for e in ext)
    assert total < 10 * 1024 * 1024  # holes are not mapped


def test_check_file_report(data_file):
    path, data = data_file
    rep = check_file(path)
    assert rep.size == len(data)
    assert rep.tier in (PathTier.DIRECT_NVME, PathTier.DIRECT, PathTier.BUFFERED)
    assert rep.reasons
    # the verdict mirror of the reference's CHECK_FILE boolean
    assert rep.supported == (rep.tier != PathTier.BUFFERED)
    assert rep.fs_type != ""


def test_check_file_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        check_file(str(tmp_path / "nope.bin"))


class TestCheckStriped:
    def test_striped_set_reports_worst_member_tier(self, tmp_path, rng):
        """check_file on a StripedFile checks every member and reports the
        set at the worst member tier (the reference's md-raid0 rule: fast
        path only when every member qualifies)."""
        from strom.delivery.core import StripedFile
        from strom.engine.raid0 import stripe_file
        from strom.probe.check import _TIER_RANK, check_file

        data = rng.integers(0, 256, 256 * 1024, dtype=np.uint8)
        src = tmp_path / "src.bin"
        data.tofile(src)
        members = [str(tmp_path / f"cm{i}.bin") for i in range(3)]
        stripe_file(str(src), members, 8192)
        sf = StripedFile(tuple(members), 8192)

        rep = check_file(sf)
        member_reps = [check_file(m) for m in members]
        worst = min((m.tier for m in member_reps), key=_TIER_RANK.__getitem__)
        assert rep.tier is worst
        assert rep.size == sf.size
        assert rep.extents == sum(m.extents for m in member_reps)
        assert any("raid0 set: 3 members" in r for r in rep.reasons)
        assert all(os.path.abspath(m) in rep.path for m in members)

    def test_module_level_alias_resolution(self, tmp_path, rng):
        """strom.check_file on an aliased path checks the striped set, and
        does NOT create a context when none exists."""
        import strom
        from strom.engine.raid0 import stripe_file

        data = rng.integers(0, 256, 64 * 1024, dtype=np.uint8)
        src = tmp_path / "asrc.bin"
        data.tofile(src)
        members = [str(tmp_path / f"acm{i}.bin") for i in range(2)]
        stripe_file(str(src), members, 4096)

        # no context yet: plain path semantics, no side-effect context
        strom.close()
        rep_plain = strom.check_file(members[0])
        assert strom._ctx is None, "check_file must not create a context"

        strom.register_striped(str(tmp_path / "avirt.bin"), members, 4096)
        try:
            rep = strom.check_file(str(tmp_path / "avirt.bin"))
            assert any("raid0 set: 2 members" in r for r in rep.reasons)
            assert rep.tier is rep_plain.tier
        finally:
            strom.close()


def test_check_file_reports_residency(tmp_path, rng):
    """cached_frac: 0 cold, 1.0 warm (the residency hybrid's input signal),
    None only when no probe exists on the kernel."""
    from strom.probe.check import check_file
    from strom.probe.residency import cached_pages, drop_cache

    data = rng.integers(0, 256, 2 * 1024 * 1024, dtype=np.uint8)
    p = str(tmp_path / "res.bin")
    data.tofile(p)
    fd = os.open(p, os.O_RDONLY)
    try:
        if cached_pages(fd, 0, 4096) is None:
            pytest.skip("no residency probe on this kernel")
    finally:
        os.close(fd)
    drop_cache(p)
    rep = check_file(p, want_extents=False)
    assert rep.cached_frac == 0.0
    with open(p, "rb") as f:
        f.read()
    rep = check_file(p, want_extents=False)
    assert rep.cached_frac == 1.0
    assert any("resident" in r for r in rep.reasons)
