"""Probe layer: FIEMAP, O_DIRECT probing, check_file tiers (SURVEY.md §4.2)."""

import os

import numpy as np
import pytest

from strom.probe import check_file, fiemap, probe_dio
from strom.probe.check import PathTier
from strom.probe.fiemap import coverage


def test_probe_dio_regular_file(data_file):
    path, _ = data_file
    dio = probe_dio(path)
    assert dio.supported in (True, False)
    if dio.supported:
        assert dio.mem_align > 0 and dio.offset_align > 0
        assert dio.mem_align % 512 == 0 or dio.mem_align in (1, 512)


def test_fiemap_covers_file(data_file):
    path, data = data_file
    try:
        ext = fiemap(path)
    except OSError:
        pytest.skip("fiemap unsupported on this filesystem")
    assert ext, "expected at least one extent"
    assert coverage([e for e in ext if e.is_reliable], len(data)) >= 0.99


def test_fiemap_on_sparse_file(tmp_path):
    p = tmp_path / "sparse.bin"
    with open(p, "wb") as f:
        f.seek(10 * 1024 * 1024 - 1)
        f.write(b"\x01")
    try:
        ext = fiemap(str(p))
    except OSError:
        pytest.skip("fiemap unsupported")
    total = sum(e.length for e in ext)
    assert total < 10 * 1024 * 1024  # holes are not mapped


def test_check_file_report(data_file):
    path, data = data_file
    rep = check_file(path)
    assert rep.size == len(data)
    assert rep.tier in (PathTier.DIRECT_NVME, PathTier.DIRECT, PathTier.BUFFERED)
    assert rep.reasons
    # the verdict mirror of the reference's CHECK_FILE boolean
    assert rep.supported == (rep.tier != PathTier.BUFFERED)
    assert rep.fs_type != ""


def test_check_file_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        check_file(str(tmp_path / "nope.bin"))
