"""Peer fabric v2 (ISSUE 20): batched pipelined transport, consistent-hash
directory with re-ownership, decoded-frame serving, conn pool + auth.

Covers the acceptance invariants directly:

- a gather's worth of peer misses rides ONE round trip (client
  ``peer_batches``/server ``peer_batch_serves`` accounting) with bytes
  bit-identical to the unbatched v1 wire, including the pipelined
  multi-chunk path,
- the per-peer downgrade latch against an old-protocol peer: the batch
  attempt fails once, the traced attempt fails once, then every fetch
  rides the raw v1 op — correct bytes throughout, never fatal,
- persistent conn pool: dials amortised across fetches
  (``peer_conn_reuse_ratio``), stale pooled conns re-probed after a peer
  restart,
- shared-key auth: wrong/missing key is a clean counted refusal
  (``peer_auth_rejects``) with engine fallback; matching keys serve;
  a keyless server tolerates a keyed client (mixed-config rollout),
- HashRing determinism (membership-order independent) + minimal movement
  (only the dead member's keys move), ExtentDirectory death publish/poll
  epochs through a shared rendezvous dir,
- the kill-a-host story end to end: breaker trip publishes the death,
  the skip window keeps probes cheap (``peer_skips``), the poll re-owns
  the keys (epoch bump) and the fetch recovers off the survivor —
  then the full subprocess fleet: survivors bit-identical to the
  single-process oracle with the victim gone mid-run,
- decoded-frame serving: one host's DecodedCache answers a peer's
  ``fetch_frame`` with crop-ready RGB (zero decodes on the asker),
  fingerprint-split, miss-counted,
- the Autotuner knobs over the live tier (batch size + pool depth)
  profile round-trip.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.dist.directory import ExtentDirectory, HashRing
from strom.dist.launch import measure_ingest
from strom.dist.peers import (PeerProtocolError, PeerTier,
                              decode_batch_request, decode_request,
                              encode_batch_request, recv_frame, send_frame,
                              ST_HIT)


def _cfg(**kw):
    base = dict(engine="python", queue_depth=8, num_buffers=8,
                hot_cache_bytes=64 << 20, hot_cache_admit="always")
    base.update(kw)
    return StromConfig(**base)


def _fixture(tmp_path, name="data.bin", n=256 * 1024, seed=0):
    p = str(tmp_path / name)
    payload = np.random.default_rng(seed).integers(0, 255, n, dtype=np.uint8)
    payload.tofile(p)
    return p, payload


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- batch frame codec units -------------------------------------------------

def test_batch_request_roundtrip():
    keys = [("/a.bin", 0, 4096), ("/b.bin", 4096, 8192, "rgb8/turbo")]
    raw = encode_batch_request(keys, trace=(7, 9, 1.5, "read"),
                               codec="lz4")
    got, trace, codec = decode_batch_request(raw)
    assert [(k[1], k[2], k[3], k[4]) for k in got] == \
        [("/a.bin", 0, 4096, None), ("/b.bin", 4096, 8192, "rgb8/turbo")]
    assert got[0][0] == 0 and got[1][0] == 1  # extent vs frame kind
    assert trace["req"] == 7 and trace["flow"] == 9
    assert codec == "lz4"


def test_batch_request_rejects_garbage():
    with pytest.raises(PeerProtocolError):
        decode_batch_request(b"\x05\x00")
    with pytest.raises(PeerProtocolError):
        decode_batch_request(encode_batch_request([("p", 0, 8)]) + b"x")
    with pytest.raises(ValueError):
        encode_batch_request([])


# -- batched transport: one RTT per gather, bit-identical --------------------

def test_batched_fetch_many_single_rtt_bit_identical(tmp_path):
    """Six peer misses ride ONE batch round trip (client counts 1 batch /
    6 extents, server counts 1 batch serve / 6 item serves) and the bytes
    match an unbatched tier's fetch of the same ranges byte for byte."""
    p, payload = _fixture(tmp_path)
    A = StromContext(_cfg())
    B = StromContext(_cfg())
    U = StromContext(_cfg(dist_batch_max_extents=0))  # v1 wire
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        U.attach_peers({0: addr}, owner_fn=lambda path: 0)
        ranges = [(p, i * 4096, (i + 1) * 4096) for i in range(6)]

        batched = B.peer_tier.fetch_many(ranges)
        unbatched = U.peer_tier.fetch_many(ranges)
        for (path, lo, hi), bv, uv in zip(ranges, batched, unbatched):
            assert bytes(bv) == payload[lo:hi].tobytes()
            assert bytes(bv) == bytes(uv)

        bst = B.peer_tier.stats()
        assert bst["peer_batches"] == 1
        assert bst["peer_batch_extents"] == 6
        assert bst["peer_hits"] == 6
        assert bst["peer_hit_bytes"] == 6 * 4096
        assert bst["peer_rtt_per_extent_us"] > 0
        ust = U.peer_tier.stats()
        assert ust["peer_batches"] == 0
        assert ust["peer_hits"] == 6
        sst = A.peer_server.stats()
        assert sst["peer_batch_serves"] == 1
    finally:
        A.close()
        B.close()
        U.close()


def test_pipelined_chunks_bit_identical(tmp_path):
    """batch_max_extents=2 over 8 ranges = 4 pipelined chunks on one
    conn (chunk k+1's request is in flight while chunk k drains) — same
    bytes, batch accounting reflects the chunking."""
    p, payload = _fixture(tmp_path)
    A, B = StromContext(_cfg(dist_batch_max_extents=2)), None
    B = StromContext(_cfg(dist_batch_max_extents=2))
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        ranges = [(p, i * 8192, i * 8192 + 4096) for i in range(8)]
        got = B.peer_tier.fetch_many(ranges)
        for (path, lo, hi), d in zip(ranges, got):
            assert bytes(d) == payload[lo:hi].tobytes()
        st = B.peer_tier.stats()
        assert st["peer_batches"] == 4
        assert st["peer_batch_extents"] == 8
    finally:
        A.close()
        B.close()


def test_batch_mixes_hits_and_misses(tmp_path):
    """A batch whose tail ranges the owner never warmed answers per-item
    hit/miss — misses fall to the asker's engine via the consult, hits
    skip it."""
    p, payload = _fixture(tmp_path)
    A, B = StromContext(_cfg()), StromContext(_cfg())
    try:
        addr = A.serve_peers()
        A.pread(p, 0, 16 * 1024)  # only the head is hot on the owner
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        ranges = [(p, 0, 4096), (p, 4096, 8192),
                  (p, 128 * 1024, 132 * 1024)]
        got = B.peer_tier.fetch_many(ranges)
        assert bytes(got[0]) == payload[:4096].tobytes()
        assert bytes(got[1]) == payload[4096:8192].tobytes()
        assert got[2] is None
        st = B.peer_tier.stats()
        assert st["peer_hits"] == 2 and st["peer_misses"] == 1
    finally:
        A.close()
        B.close()


# -- downgrade ladder vs an old-protocol peer --------------------------------

def test_old_proto_peer_downgrades_batch_then_trace_then_raw(tmp_path):
    """A stub peer speaking ONLY the raw v1 ``OP_GET`` (closes the conn on
    any op it can't parse — exactly what the pre-batch server did): the
    first gather burns one error latching batch_ok=False, the first
    single fetch burns one latching trace_ok=False, and everything after
    rides plain OP_GET with correct bytes."""
    p, payload = _fixture(tmp_path)

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    addr = f"127.0.0.1:{lsock.getsockname()[1]}"
    stop = threading.Event()

    def v1_only():
        while not stop.is_set():
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            with conn:
                while True:
                    try:
                        frame = recv_frame(conn)
                        path, lo, hi = decode_request(frame)
                        send_frame(conn, bytes([ST_HIT])
                                   + payload[lo:hi].tobytes())
                    except (OSError, PeerProtocolError, ValueError):
                        break  # unknown op/hangup: slam the conn, v1-style

    t = threading.Thread(target=v1_only, name="test-v1-peer", daemon=True)
    t.start()
    tier = PeerTier({0: addr}, owner_fn=lambda path: 0, timeout_s=2.0,
                    breaker_kwargs=dict(min_events=100))
    try:
        ranges = [(p, i * 4096, (i + 1) * 4096) for i in range(4)]
        first = tier.fetch_many(ranges)
        # the batch attempt died (error 1, batch latch), item 0's traced
        # fallback died (error 2, trace latch), items 1..3 landed raw
        info = next(iter(tier.peers_info().values()))
        assert info["batch_ok"] is False
        assert info["trace_ok"] is False
        assert first[0] is None
        for (path, lo, hi), d in zip(ranges[1:], first[1:]):
            assert bytes(d) == payload[lo:hi].tobytes()
        assert tier.stats()["peer_errors"] == 2

        # fully downgraded: every later gather is raw per-extent, no new
        # errors, no batch attempted
        second = tier.fetch_many(ranges)
        for (path, lo, hi), d in zip(ranges, second):
            assert bytes(d) == payload[lo:hi].tobytes()
        st = tier.stats()
        assert st["peer_errors"] == 2
        assert st["peer_batches"] == 0
    finally:
        stop.set()
        lsock.close()
        tier.close()
        t.join(timeout=5)


# -- conn pool ---------------------------------------------------------------

def test_conn_pool_reuse_and_restart_reprobe(tmp_path):
    """Sequential fetches ride ONE pooled conn (reuse ratio climbs); a
    peer restart leaves a stale pooled sock that costs one counted error,
    is discarded, and the next fetch re-dials clean."""
    p, payload = _fixture(tmp_path)
    port = _free_port()
    A = StromContext(_cfg())
    B = StromContext(_cfg())
    try:
        A.serve_peers(port=port)
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: f"127.0.0.1:{port}"}, owner_fn=lambda path: 0)
        for i in range(4):
            got = B.peer_tier.fetch(p, i * 4096, (i + 1) * 4096)
            assert bytes(got) == payload[i * 4096:(i + 1) * 4096].tobytes()
        st = B.peer_tier.stats()
        assert st["peer_conn_opens"] == 1
        assert st["peer_conn_reuses"] == 3
        assert st["peer_conn_reuse_ratio"] == 0.75
        info = next(iter(B.peer_tier.peers_info().values()))
        assert info["pooled_conns"] == 1

        # restart the peer on the same address (the old listener may take
        # a beat to release the port after close — retry the bind)
        A.close()
        A2 = None
        for _ in range(40):
            try:
                A2 = StromContext(_cfg())
                A2.serve_peers(port=port)
                break
            except OSError:
                A2.close()
                A2 = None
                time.sleep(0.05)
        assert A2 is not None, "peer restart could not rebind its port"
        try:
            A2.pread(p, 0, payload.nbytes)
            # the pooled conn is dead: at most a couple of probe fetches
            # burn it off, then service resumes on a fresh dial
            got = None
            for _ in range(3):
                got = B.peer_tier.fetch(p, 0, 4096)
                if got is not None:
                    break
            assert bytes(got) == payload[:4096].tobytes()
            assert B.peer_tier.stats()["peer_conn_opens"] >= 2
        finally:
            A2.close()
    finally:
        B.close()
        A.close()


# -- shared-key auth ---------------------------------------------------------

def test_auth_missing_or_wrong_key_cleanly_refused(tmp_path):
    p, payload = _fixture(tmp_path)
    A = StromContext(_cfg(dist_auth_key="sekrit"))
    Bnone = StromContext(_cfg())
    Bwrong = StromContext(_cfg(dist_auth_key="wrong"))
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        for B in (Bnone, Bwrong):
            B.attach_peers({0: addr}, owner_fn=lambda path: 0)
            # the consult degrades to the engine: bytes stay correct
            got = B.pread(p, 0, 4096)
            assert bytes(got) == payload[:4096].tobytes()
            assert B.peer_tier.stats()["peer_hits"] == 0
            assert B.peer_tier.stats()["peer_errors"] >= 1
        assert A.peer_server.stats()["peer_auth_rejects"] >= 2
    finally:
        A.close()
        Bnone.close()
        Bwrong.close()


def test_auth_matching_key_serves(tmp_path):
    p, payload = _fixture(tmp_path)
    A = StromContext(_cfg(dist_auth_key="sekrit"))
    B = StromContext(_cfg(dist_auth_key="sekrit"))
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        got = B.peer_tier.fetch_many([(p, 0, 4096), (p, 4096, 8192)])
        assert bytes(got[0]) == payload[:4096].tobytes()
        assert bytes(got[1]) == payload[4096:8192].tobytes()
        assert A.peer_server.stats()["peer_auth_rejects"] == 0
        # the handshake rode the same pooled conn the batch then used
        assert B.peer_tier.stats()["peer_conn_opens"] == 1
    finally:
        A.close()
        B.close()


def test_keyless_server_tolerates_keyed_client(tmp_path):
    """Mixed-config rollout: a server without a key answers the auth
    handshake permissively so a keyed client keeps fetching."""
    p, payload = _fixture(tmp_path)
    A = StromContext(_cfg())
    B = StromContext(_cfg(dist_auth_key="sekrit"))
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        got = B.peer_tier.fetch(p, 0, 4096)
        assert bytes(got) == payload[:4096].tobytes()
    finally:
        A.close()
        B.close()


# -- hash ring + extent directory --------------------------------------------

def test_hash_ring_deterministic_and_minimal_movement():
    members = list(range(4))
    r1 = HashRing(members)
    r2 = HashRing(list(reversed(members)))  # membership ORDER is identity
    keys = [f"shard{i}.bin" for i in range(500)]
    assert [r1.owner(k) for k in keys] == [r2.owner(k) for k in keys]
    # killing one member moves EXACTLY its keys, nobody else's
    survivors = HashRing([m for m in members if m != 2])
    moved = owned = 0
    for k in keys:
        if r1.owner(k) == 2:
            owned += 1
        if r1.owner(k) != survivors.owner(k):
            moved += 1
            assert r1.owner(k) == 2, f"{k} moved off a LIVE owner"
    assert owned > 0 and moved == owned


def test_directory_death_publish_poll_epochs(tmp_path):
    """Two directories sharing a rendezvous dir: one publishes a death,
    the other's poll applies it (epoch bump, owner excluded); mark_alive
    restores the member and bumps again."""
    d1 = ExtentDirectory(["a", "b", "c"], "a", rendezvous_dir=str(tmp_path))
    d2 = ExtentDirectory(["a", "b", "c"], "b", rendezvous_dir=str(tmp_path))
    assert d1.epoch == 0 and sorted(d1.live) == ["a", "b", "c"]
    d1.mark_dead("c")
    assert os.path.exists(str(tmp_path / "ring_dead_c"))
    assert d2.poll() is True
    assert d2.epoch == 1
    assert "c" not in d2.live
    # both sides converge to the identical post-death ring
    assert d1.poll() is True
    for k in ("x.bin", "y.bin", "z.bin"):
        assert d1.ring_owner(k) == d2.ring_owner(k)
        assert d2.ring_owner(k) != "c"
    d2.mark_alive("c")
    assert d1.poll() is True
    assert d1.epoch == 2 and "c" in d1.live


def test_reownership_skip_window_then_recovery(tmp_path):
    """The kill-a-host mechanics, deterministically: errors trip the
    breaker, the trip publishes the death (NOT yet applied), the skip
    window keeps probes cheap, the poll re-owns the key, and the fetch
    recovers off the survivor — bit-identical bytes."""
    # f4.bin: owned by "D" in the full ring, re-owned to "A" (not "me")
    # once D dies — computed from the deterministic ring, pinned here
    p, payload = _fixture(tmp_path, name="f4.bin")
    directory = ExtentDirectory(["me", "A", "D"], "me",
                                rendezvous_dir=str(tmp_path),
                                poll_interval_s=3600.0)
    assert directory.ring_owner(p) == "D"
    A = StromContext(_cfg())
    tier = None
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        tier = PeerTier({"A": addr, "D": f"127.0.0.1:{_free_port()}"},
                        directory=directory, timeout_s=0.5,
                        breaker_kwargs=dict(min_events=2, cooldown_s=3600))
        for _ in range(2):  # dead owner: counted errors, trip on the 2nd
            assert tier.fetch(p, 0, 4096) is None
        st = tier.stats()
        assert st["peer_errors"] == 2 and st["peer_breaker_trips"] == 1
        assert os.path.exists(str(tmp_path / "ring_dead_D"))
        assert directory.epoch == 0  # published, not yet applied

        # skip window: the breaker short-circuits, no new dials
        assert tier.fetch(p, 0, 4096) is None
        assert tier.stats()["peer_skips"] == 1

        # the poll applies the death: epoch bump, keys re-owned
        assert directory.poll() is True
        assert directory.owner(p) == "A"
        got = tier.fetch(p, 0, 4096)
        assert bytes(got) == payload[:4096].tobytes()
        st = tier.stats()
        assert st["peer_hits"] == 1
        assert st["peer_ring_epoch"] == 1
    finally:
        if tier is not None:
            tier.close()
        A.close()


def test_kill_one_host_mid_run_survivors_bit_identical(tmp_path):
    """The full fleet acceptance: rank 1 (owner of most fixture bytes at
    nproc=3) dies uncleanly after step 1; the survivors complete every
    step bit-identical to the single-process oracle, counting errors on
    the dead peer and re-owning its keys (ring epoch bump)."""
    res = measure_ingest(3, str(tmp_path), steps=8, batch=6, seq_len=16,
                         die_rank=1, die_after_step=1)
    workers = res["workers"]
    assert res["dist_ok"] == 1, workers
    assert workers[1]["rc"] == 17  # the victim vanished, as armed
    survivors = [workers[0], workers[2]]
    assert all(w["ok"] == 1 for w in survivors)
    # the death was felt: failed dials and/or breaker-skip probes
    assert sum(w.get("peer_errors", 0) + w.get("peer_skips", 0)
               for w in survivors) > 0
    # ...tripped a survivor's breaker, which PUBLISHED the death marker
    # to the rendezvous dir for fleet-wide re-ownership (whether a given
    # survivor's throttled poll APPLIES it before its last fetch is
    # timing-dependent — the marker is the deterministic evidence)
    assert max(w.get("peer_breaker_trips", 0) for w in survivors) >= 1
    assert os.path.exists(str(tmp_path / "run3" / "ring_dead_1"))
    # recovery is real work, not a stall: every survivor rated > 0
    assert all(w["items_per_s"] > 0 for w in survivors)


# -- decoded-frame serving ---------------------------------------------------

def test_decoded_frame_served_cluster_wide(tmp_path):
    """A frame decoded ONCE on the owner answers a peer's fetch_frame as
    crop-ready RGB — the asker runs zero decode machinery; fingerprint
    mismatches and absent frames answer miss."""
    from strom.formats.decoded_cache import DecodedCache

    p, _ = _fixture(tmp_path, name="shots.jpgpack")
    A, B = StromContext(_cfg()), StromContext(_cfg())
    try:
        addr = A.serve_peers()
        dcache = DecodedCache(A.hot_cache, fingerprint="rgb8/turbo")
        img = np.random.default_rng(3).integers(
            0, 255, (8, 6, 3), dtype=np.uint8)
        ckey = dcache.key(p, 100, 900)
        assert dcache.offer(ckey, img) > 0
        A.attach_decoded_cache(dcache)

        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        got = B.peer_tier.fetch_frame(p, 100, 900, "rgb8/turbo")
        assert got is not None and got.shape == (8, 6, 3)
        assert np.array_equal(got, img)
        # the consult-facing wrapper rides the same wire by ckey
        got2 = B.peer_decoded_fetch(("jpegdec", p, 100, 900, "rgb8/turbo"))
        assert np.array_equal(got2, img)

        # wrong fingerprint / unknown member: clean misses
        assert B.peer_tier.fetch_frame(p, 100, 900, "rgb8/cv2") is None
        assert B.peer_tier.fetch_frame(p, 0, 50, "rgb8/turbo") is None

        bst = B.peer_tier.stats()
        assert bst["peer_frame_hits"] == 2
        assert bst["peer_frame_hit_bytes"] == 2 * img.nbytes
        assert bst["peer_frame_misses"] == 2
        sst = A.peer_server.stats()
        assert sst["peer_frame_serves"] == 2
        assert sst["peer_frame_served_bytes"] == 2 * img.nbytes
        assert sst["peer_frame_serve_misses"] == 2
        # frame traffic never pollutes the extent byte ledgers
        assert bst["peer_hit_bytes"] == 0
        assert sst["peer_served_bytes"] == 0
    finally:
        A.close()
        B.close()


def test_decoded_export_copies_out(tmp_path):
    """export() hands back an owned bytes copy (the server writes it to a
    socket long after any pin window) and refuses fingerprint drift."""
    from strom.formats.decoded_cache import DecodedCache

    ctx = StromContext(_cfg())
    try:
        dc = DecodedCache(ctx.hot_cache, fingerprint="rgb8/x")
        img = np.arange(4 * 5 * 3, dtype=np.uint8).reshape(4, 5, 3)
        dc.offer(dc.key("/s", 0, 64), img)
        got = dc.export("/s", 0, 64)
        assert got is not None
        h, w, raw = got
        assert (h, w) == (4, 5) and isinstance(raw, bytes)
        assert raw == img.tobytes()
        assert dc.export("/s", 0, 64, fingerprint="rgb8/other") is None
        assert dc.export("/nope", 0, 64) is None
    finally:
        ctx.close()


# -- autotuner knobs ---------------------------------------------------------

def test_peer_tier_knobs_profile_round_trip(tmp_path):
    from strom.tune import Autotuner, Profile
    from strom.tune.knobs import standard_knobs

    p, payload = _fixture(tmp_path)
    A, B = StromContext(_cfg()), StromContext(_cfg())
    try:
        addr = A.serve_peers()
        A.pread(p, 0, payload.nbytes)
        B.attach_peers({0: addr}, owner_fn=lambda path: 0)
        knobs = {k.name: k for k in standard_knobs(B)}
        assert "dist_batch_max_extents" in knobs
        assert "dist_conn_pool_size" in knobs
        knobs["dist_batch_max_extents"].set(32.0)
        knobs["dist_conn_pool_size"].set(4.0)
        assert B.peer_tier.batch_max_extents == 32
        assert B.peer_tier.conn_pool_size == 4

        # profile round trip: persisted knobs restart the tier where the
        # search converged, clamped onto the live bounds
        tuner = Autotuner([knobs["dist_batch_max_extents"],
                           knobs["dist_conn_pool_size"]],
                          lambda: {"objective": 1.0})
        path = str(tmp_path / "profile.json")
        tuner.profile().save(path)
        knobs["dist_batch_max_extents"].set(64.0)
        knobs["dist_conn_pool_size"].set(1.0)
        applied = tuner.apply_profile(Profile.load(path))
        assert applied == 2
        assert B.peer_tier.batch_max_extents == 32
        assert B.peer_tier.conn_pool_size == 4
        # clamp floor: 0 would turn the wire off — the tuner can't
        Profile("arm", {"dist_batch_max_extents": 0.0,
                        "dist_conn_pool_size": 0.0}).save(path)
        tuner.apply_profile(Profile.load(path))
        assert B.peer_tier.batch_max_extents == 1
        assert B.peer_tier.conn_pool_size == 1
        # the knobs steer live transport, not a snapshot: fetches still
        # serve bit-identical after the moves
        got = B.peer_tier.fetch_many([(p, 0, 4096), (p, 4096, 8192)])
        assert bytes(got[0]) == payload[:4096].tobytes()
        assert bytes(got[1]) == payload[4096:8192].tobytes()
    finally:
        A.close()
        B.close()
