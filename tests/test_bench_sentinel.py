"""Bench regression sentinel (ISSUE 6 tentpole) + its tier-1 CI wiring.

Two layers: synthetic artifact sets prove the verdict logic (good /
regressed / invalid / grandfathered), and the CI-wiring test runs the
sentinel over the REPO'S OWN checked-in BENCH_r*.json / MULTICHIP_r*.json
with the pre-sentinel history pinned as baseline — so a future round that
regresses or ships an invalid artifact fails this suite loudly, while
today's history (r05 is rc=124/parsed=null) stays green.
"""

import importlib.util
import json
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SPEC = importlib.util.spec_from_file_location(
    "bench_sentinel", os.path.join(_ROOT, "tools", "bench_sentinel.py"))
sentinel = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(sentinel)

# the rounds checked in when the sentinel landed: their verdicts are
# baseline (they feed history; they don't gate). A NEW round appended
# after this pin gates normally — bump the pin only with a round that
# passed the gate.
GRANDFATHER_THROUGH = "BENCH_r05.json"


def mk_round(tmp_path, name, binding=None, rc=0, parsed="auto", **fields):
    doc = {"n": 1, "cmd": "python bench.py", "rc": rc}
    if parsed == "auto":
        inner = {"metric": "ssd2hbm_bandwidth", "value": 1.0,
                 "unit": "GB/s", **fields}
        if binding is not None:
            inner["binding"] = binding
        doc["parsed"] = inner
        doc["tail"] = json.dumps(inner)
    else:
        doc["parsed"] = parsed
        doc["tail"] = None
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


class TestLoadRound:
    def test_valid(self, tmp_path):
        p = mk_round(tmp_path, "BENCH_r01.json",
                     binding={"vs_link": 0.99})
        r = sentinel.load_round(p)
        assert r["valid"] and r["reason"] == ""
        assert sentinel.metric_value(r["data"], "vs_link") == 0.99

    def test_rc124_parsed_null_is_invalid_not_crash(self, tmp_path):
        p = mk_round(tmp_path, "BENCH_r05.json", rc=124, parsed=None)
        r = sentinel.load_round(p)
        assert not r["valid"]
        assert "rc=124" in r["reason"]

    def test_unreadable_is_invalid(self, tmp_path):
        p = tmp_path / "BENCH_r03.json"
        p.write_text("{nope")
        r = sentinel.load_round(str(p))
        assert not r["valid"] and "unreadable" in r["reason"]

    def test_rc0_no_metrics_is_invalid(self, tmp_path):
        p = tmp_path / "BENCH_r02.json"
        p.write_text(json.dumps({"rc": 0, "parsed": None, "tail": "junk"}))
        r = sentinel.load_round(str(p))
        assert not r["valid"]


class TestVerdicts:
    def test_good_trajectory_ok(self, tmp_path):
        paths = [mk_round(tmp_path, f"BENCH_r0{i}.json",
                          binding={"vs_link": 0.98 + i / 1000})
                 for i in (1, 2, 3)]
        v = sentinel.run_sentinel(paths, band=0.25, known_invalid=set())
        assert v["verdict"] == "ok"
        assert v["regressions"] == [] and v["invalid_rounds"] == []

    def test_regression_beyond_band_fails(self, tmp_path):
        paths = [
            mk_round(tmp_path, "BENCH_r01.json", binding={"vs_link": 0.99}),
            mk_round(tmp_path, "BENCH_r02.json", binding={"vs_link": 0.98}),
            mk_round(tmp_path, "BENCH_r03.json", binding={"vs_link": 0.50}),
        ]
        v = sentinel.run_sentinel(paths, band=0.25, known_invalid=set())
        assert v["verdict"] == "fail"
        hit = next(h for h in v["regressions"] if h["metric"] == "vs_link")
        assert hit["latest_round"] == "BENCH_r03.json"
        assert hit["previous"] == 0.98 and hit["best"] == 0.99

    def test_noise_inside_band_passes(self, tmp_path):
        paths = [
            mk_round(tmp_path, "BENCH_r01.json", binding={"vs_link": 0.99}),
            mk_round(tmp_path, "BENCH_r02.json", binding={"vs_link": 0.90}),
        ]
        v = sentinel.run_sentinel(paths, band=0.25, known_invalid=set())
        assert v["verdict"] == "ok"

    def test_one_bad_round_against_good_history_needs_both(self, tmp_path):
        """Worse than previous but NOT worse than best-of-history (or vice
        versa) doesn't fire: single-round noise isn't a regression."""
        paths = [
            mk_round(tmp_path, "BENCH_r01.json", binding={"vs_link": 0.50}),
            mk_round(tmp_path, "BENCH_r02.json", binding={"vs_link": 0.99}),
            mk_round(tmp_path, "BENCH_r03.json", binding={"vs_link": 0.60}),
        ]
        # 0.60 is worse than prev 0.99 beyond band, but NOT beyond-band
        # worse than best-of-history-min... best for "up" is max(0.5,0.99)
        # = 0.99 → 0.60 < 0.99*0.75 → fires. Use a shape where history
        # already contains a comparable low: gate on both = no fire when
        # best is low too.
        paths2 = [
            mk_round(tmp_path, "BENCH_r11.json", binding={"vs_link": 0.55}),
            mk_round(tmp_path, "BENCH_r12.json", binding={"vs_link": 0.60}),
            mk_round(tmp_path, "BENCH_r13.json", binding={"vs_link": 0.50}),
        ]
        v2 = sentinel.run_sentinel(paths2, band=0.25, known_invalid=set())
        assert all(h["metric"] != "vs_link" for h in v2["regressions"])
        v = sentinel.run_sentinel(paths, band=0.25, known_invalid=set())
        assert any(h["metric"] == "vs_link" for h in v["regressions"])

    def test_stall_counter_small_jitter_tolerated(self, tmp_path):
        paths = [
            mk_round(tmp_path, "BENCH_r01.json",
                     binding={"train_data_stalls": 0}),
            mk_round(tmp_path, "BENCH_r02.json",
                     binding={"train_data_stalls": 1}),
        ]
        v = sentinel.run_sentinel(paths, band=0.25, known_invalid=set())
        assert v["verdict"] == "ok"  # 0 -> 1 stall is jitter (ABS_SLACK)
        paths.append(mk_round(tmp_path, "BENCH_r03.json",
                              binding={"train_data_stalls": 40}))
        v = sentinel.run_sentinel(paths, band=0.25, known_invalid=set())
        assert any(h["metric"] == "train_data_stalls"
                   for h in v["regressions"])

    def test_invalid_round_fails_unless_grandfathered(self, tmp_path):
        paths = [
            mk_round(tmp_path, "BENCH_r01.json", binding={"vs_link": 0.99}),
            mk_round(tmp_path, "BENCH_r02.json", rc=124, parsed=None),
        ]
        v = sentinel.run_sentinel(paths, band=0.25, known_invalid=set())
        assert v["verdict"] == "fail"
        assert v["invalid_rounds"] == ["BENCH_r02.json"]
        v2 = sentinel.run_sentinel(paths, band=0.25,
                                   known_invalid={"BENCH_r02.json"})
        assert v2["verdict"] == "ok"
        assert v2["grandfathered_invalid"] == ["BENCH_r02.json"]

    def test_grandfather_through_pins_history_but_gates_future(self,
                                                               tmp_path):
        hist = [
            mk_round(tmp_path, "BENCH_r01.json", binding={"vs_link": 0.99}),
            mk_round(tmp_path, "BENCH_r02.json", rc=124, parsed=None),
        ]
        v = sentinel.run_sentinel(hist, band=0.25, known_invalid=set(),
                                  grandfather_through="BENCH_r02.json")
        assert v["verdict"] == "ok"
        # a FUTURE invalid round past the pin still gates
        future = hist + [mk_round(tmp_path, "BENCH_r03.json", rc=1,
                                  parsed=None)]
        v2 = sentinel.run_sentinel(future, band=0.25, known_invalid=set(),
                                   grandfather_through="BENCH_r02.json")
        assert v2["verdict"] == "fail"
        # ...and so does a future regression
        future2 = hist + [mk_round(tmp_path, "BENCH_r04.json",
                                   binding={"vs_link": 0.40})]
        v3 = sentinel.run_sentinel(future2, band=0.25, known_invalid=set(),
                                   grandfather_through="BENCH_r02.json")
        assert v3["verdict"] == "fail"

    def test_multichip_ok_shrink_fails(self, tmp_path):
        a = tmp_path / "MULTICHIP_r01.json"
        a.write_text(json.dumps({"n_devices": 16, "rc": 0, "ok": 8,
                                 "skipped": 0}))
        b = tmp_path / "MULTICHIP_r02.json"
        b.write_text(json.dumps({"n_devices": 16, "rc": 0, "ok": 6,
                                 "skipped": 2}))
        v = sentinel.run_sentinel([str(a), str(b)], band=0.25,
                                  known_invalid=set())
        assert any(h["metric"] == "multichip_ok" for h in v["regressions"])


class TestCli:
    def test_main_exits_nonzero_on_invalid(self, tmp_path, capsys):
        paths = [
            mk_round(tmp_path, "BENCH_r01.json", binding={"vs_link": 0.99}),
            mk_round(tmp_path, "BENCH_r02.json", rc=124, parsed=None),
        ]
        assert sentinel.main(paths) == 1
        out = capsys.readouterr().out
        assert "INVALID" in out and "| vs_link |" in out

    def test_check_mode_emits_verdict_json(self, tmp_path, capsys):
        paths = [mk_round(tmp_path, "BENCH_r01.json",
                          binding={"vs_link": 0.99})]
        assert sentinel.main(["--check"] + paths) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["verdict"] == "ok"

    def test_json_out(self, tmp_path):
        paths = [mk_round(tmp_path, "BENCH_r01.json",
                          binding={"vs_link": 0.99})]
        out = tmp_path / "v.json"
        assert sentinel.main(["--json", str(out)] + paths) == 0
        assert json.loads(out.read_text())["verdict"] == "ok"


class TestRepoArtifacts:
    """The CI wiring (ISSUE 6 satellite): the sentinel runs over the
    checked-in artifacts every tier-1 run."""

    def test_r05_fails_the_plain_gate(self):
        """Acceptance: `python tools/bench_sentinel.py BENCH_r0*.json`
        exits nonzero on the r05 invalid artifact."""
        import glob as _g

        paths = sorted(_g.glob(os.path.join(_ROOT, "BENCH_r0*.json")))
        assert paths, "checked-in BENCH artifacts missing"
        v = sentinel.run_sentinel(paths, band=0.25, known_invalid=set())
        assert v["verdict"] == "fail"
        assert "BENCH_r05.json" in v["invalid_rounds"]

    def test_checked_in_trajectory_gates_future_rounds(self, capsys):
        """`--check --grandfather-through <pin>`: green on today's
        history; a future bad round past the pin flips it red (proved on
        synthetic futures in TestVerdicts)."""
        rc = sentinel.main(["--check", "--grandfather-through",
                            GRANDFATHER_THROUGH])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0, f"sentinel gate failed: {doc}"
        assert doc["verdict"] == "ok"
        # the r05 invalidity is still REPORTED (grandfathered, not hidden)
        assert "BENCH_r05.json" in doc["invalid_rounds"]
        assert "BENCH_r05.json" in doc["grandfathered_invalid"]


def test_chaos_slowdown_bands_relatively():
    """RATIO_DOWN metrics (ISSUE 9): chaos_slowdown sits near 1.0, so the
    count-sized ABS_SLACK (2.0) would let it reach ~3.2 before the gate
    fired — it must band relatively instead, like the "up" direction."""
    series = [("r1", 1.2), ("r2", 1.2), ("r3", 1.8)]
    v = sentinel.check_metric("chaos_slowdown", "down", series, band=0.25)
    assert v is not None, "1.2 -> 1.8 at band=0.25 must fire"
    # a count-like "down" metric with the same numbers stays inside the
    # absolute slack (0 -> 1 stall is jitter, the documented contract)
    assert sentinel.check_metric("resnet_train_data_stalls", "down",
                                 series, band=0.25) is None
