"""Decode-path parity (ISSUE 2 tentpole): reduced-scale JPEG decode chosen
from the SOF header, direct-to-slot decode workers, and overlapped
per-device shard delivery — each golden-tested against the path it
replaces (full-scale decode / np.stack / serial puts), plus the per-sample
decode-failure policy and the cv2 global-thread-count restore."""

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.formats.jpeg import (DecodePool, decode_jpeg, make_train_transform,
                                parse_jpeg_dims, random_resized_crop,
                                reduced_denom)
from strom.parallel.mesh import make_mesh
from strom.utils.stats import global_stats


def smooth_jpeg(h, w, quality=95):
    """Low-frequency gradient image: JPEG encodes it near-losslessly, so the
    full-scale and reduced-scale decode paths agree within a small pixel
    tolerance (noise images would measure codec error, not the geometry)."""
    yy, xx = np.mgrid[0:h, 0:w]
    img = np.stack([yy * 255 / max(h - 1, 1),
                    xx * 255 / max(w - 1, 1),
                    (yy + xx) * 255 / max(h + w - 2, 1)],
                   axis=-1).astype(np.uint8)
    ok, buf = cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, quality])
    assert ok
    return img, buf.tobytes()


def noise_jpeg(rng, h, w):
    img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
    ok, buf = cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, 90])
    assert ok
    return buf.tobytes()


def philox(seed, row):
    return np.random.Generator(np.random.Philox(key=[seed, row]))


# ------------------------------------------------------- SOF header parsing
class TestSofParser:
    @pytest.mark.parametrize("h,w", [(8, 8), (48, 64), (201, 317),
                                     (512, 512), (1024, 768)])
    def test_dims_match_decode(self, h, w):
        _, data = smooth_jpeg(h, w)
        assert parse_jpeg_dims(data) == (h, w)
        assert decode_jpeg(data).shape[:2] == (h, w)

    def test_progressive_sof2(self):
        img, _ = smooth_jpeg(120, 90)
        ok, buf = cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_PROGRESSIVE, 1])
        assert ok
        assert parse_jpeg_dims(buf.tobytes()) == (120, 90)

    def test_ndarray_input(self):
        _, data = smooth_jpeg(40, 60)
        assert parse_jpeg_dims(np.frombuffer(data, np.uint8)) == (40, 60)

    def test_non_jpeg_returns_none(self):
        assert parse_jpeg_dims(b"definitely not a jpeg") is None
        img, _ = smooth_jpeg(16, 16)
        ok, png = cv2.imencode(".png", img)
        assert ok
        assert parse_jpeg_dims(png.tobytes()) is None

    def test_truncated_header_returns_none(self):
        _, data = smooth_jpeg(64, 64)
        assert parse_jpeg_dims(data[:4]) is None

    def test_denom_rule(self):
        # inputs are CROP dims: the reduced crop must still cover the target
        assert reduced_denom(1800, 1800, 224) == 8
        assert reduced_denom(500, 600, 224) == 2
        assert reduced_denom(300, 300, 224) == 1
        # the SHORTER side gates eligibility
        assert reduced_denom(4000, 100, 224) == 1
        assert reduced_denom(448, 448, 224) == 2
        assert reduced_denom(100, 100, 0) == 1


# --------------------------------------------- group 1: reduced-scale parity
class TestReducedScaleParity:
    def test_reduced_decode_shapes(self):
        _, data = smooth_jpeg(201, 317)
        for d in (2, 4, 8):
            img = decode_jpeg(data, reduced=d)
            # libjpeg reduced sizes are ceil(dim/d)
            assert img.shape == (-(-201 // d), -(-317 // d), 3)

    @pytest.mark.parametrize("h,w,size", [(512, 512, 64), (448, 640, 56),
                                          (256, 256, 96)])
    def test_matches_full_scale_within_tolerance(self, h, w, size):
        """Golden parity: reduced-scale decode + rescaled crop geometry
        lands within a small pixel tolerance of the full-scale path, with
        identical shape/dtype and an identical RNG stream."""
        _, data = smooth_jpeg(h, w)
        tf_full = make_train_transform(size, reduced_scale=False)
        tf_red = make_train_transform(size, reduced_scale=True)
        hits0 = sum(global_stats.counter(f"decode_reduced_hits_{d}").value
                    for d in (2, 4, 8))
        for seed in range(6):
            ra, rb = philox(1, seed), philox(1, seed)
            full = tf_full(data, ra)
            red = tf_red(data, rb)
            assert red.shape == full.shape == (size, size, 3)
            assert red.dtype == full.dtype == np.uint8
            diff = np.abs(full.astype(int) - red.astype(int))
            assert diff.mean() < 4.0 and diff.max() < 32, \
                (seed, diff.mean(), diff.max())
            # the two paths consumed the same number of RNG draws —
            # checkpoint-resume determinism does not depend on the knob
            assert ra.random() == rb.random()
        # the reduced path actually engaged across the seeds
        assert sum(global_stats.counter(f"decode_reduced_hits_{d}").value
                   for d in (2, 4, 8)) > hits0

    def test_hit_counters_bump(self):
        """Near-full-image crops of a 512^2 source cover a 32^2 target at
        1/8 scale, so the denom-8 counter must fire."""
        _, data = smooth_jpeg(512, 512)
        before = global_stats.counter("decode_reduced_hits_8").value
        make_train_transform(32, reduced_scale=True,
                             scale=(0.95, 1.0))(data, philox(0, 0))
        assert global_stats.counter("decode_reduced_hits_8").value == before + 1

    def test_small_crop_rides_full_path(self):
        """A crop below size*2 on its shorter side must NOT decode reduced —
        it would be upscaled from 1/d pixels where the full path downsamples
        real ones (quality, not just speed)."""
        _, data = smooth_jpeg(100, 100)  # crops can never reach 96*2
        snaps = {d: global_stats.counter(f"decode_reduced_hits_{d}").value
                 for d in (2, 4, 8)}
        out = make_train_transform(96, reduced_scale=True)(data, philox(0, 1))
        assert out.shape == (96, 96, 3)
        for d, v in snaps.items():
            assert global_stats.counter(f"decode_reduced_hits_{d}").value == v


# ------------------------------------------- group 2: direct-to-slot decode
class TestSlotDecode:
    def test_out_path_bit_identical_to_alloc_path(self, rng):
        img = rng.integers(0, 256, (100, 80, 3), dtype=np.uint8)
        for seed in range(8):  # both flip branches get exercised
            ref = random_resized_crop(img, 32, philox(2, seed))
            out = np.empty((32, 32, 3), np.uint8)
            got = random_resized_crop(img, 32, philox(2, seed), out=out)
            assert got is out
            np.testing.assert_array_equal(got, ref)

    def test_map_into_bit_identical_to_stack(self, rng):
        blobs = [noise_jpeg(rng, 60 + 7 * i, 90 - 5 * i) for i in range(6)]
        tf = make_train_transform(32)
        with DecodePool(3) as pool:
            ref = np.stack(pool.map(tf, blobs,
                                    [philox(3, i) for i in range(6)]))
            out = np.empty((6, 32, 32, 3), np.uint8)
            pool.map_into(tf, blobs, [philox(3, i) for i in range(6)], out)
        np.testing.assert_array_equal(out, ref)

    def test_decode_failure_zeroes_row_not_batch(self, rng):
        blobs = [noise_jpeg(rng, 50, 50), b"definitely not a jpeg",
                 noise_jpeg(rng, 50, 50)]
        tf = make_train_transform(16)
        before = global_stats.counter("decode_errors").value
        with DecodePool(2) as pool:
            out = np.full((3, 16, 16, 3), 255, np.uint8)
            pool.map_into(tf, blobs, [philox(4, i) for i in range(3)], out)
            assert pool.decode_errors == 1
        assert out[0].any()      # good rows decoded
        assert not out[1].any()  # bad row zeroed
        assert out[2].any()
        assert global_stats.counter("decode_errors").value == before + 1

    def test_map_keeps_abort_semantics(self, rng):
        """The legacy stack path (plain map) still aborts on garbage — the
        zero-substitution policy is a slot-path (map_into) contract."""
        with DecodePool(2) as pool:
            with pytest.raises(ValueError):
                pool.map(decode_jpeg, [b"garbage"])


# --------------------------------------- group 3: overlapped shard delivery
@pytest.fixture(scope="module")
def ctx():
    c = StromContext(StromConfig(engine="python", queue_depth=8,
                                 num_buffers=8))
    yield c
    c.close()


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"dp": 8}, devices=jax.devices()[:8])


@pytest.fixture(scope="module")
def wds_tar(tmp_path_factory):
    from tests.test_formats import make_wds_shard

    rng = np.random.default_rng(11)
    td = tmp_path_factory.mktemp("decode_wds")
    samples = []
    for i in range(16):
        # mixed sizes: some eligible for reduced decode at size 32, some not
        h = 40 + 8 * i
        samples.append((f"s{i:04d}", {"jpg": noise_jpeg(rng, h, h + 10),
                                      "cls": str(i % 10).encode()}))
    p = str(td / "shard.tar")
    make_wds_shard(p, samples)
    return p


class TestOverlappedDelivery:
    def _pipeline(self, ctx, mesh, tar, **kw):
        from strom.pipelines import make_wds_vision_pipeline

        return make_wds_vision_pipeline(
            ctx, [tar], batch=8, image_size=32,
            sharding=NamedSharding(mesh, P("dp", None, None, None)),
            shuffle=False, decode_workers=4, seed=5, **kw)

    def _batches(self, pipe, n=2):
        out = []
        with pipe:
            for _ in range(n):
                imgs, lbls = next(pipe)
                out.append((np.asarray(imgs).copy(), np.asarray(lbls).copy()))
        return out

    def test_overlapped_puts_match_serial(self, ctx, mesh, wds_tar):
        """The completion-ordered per-device puts assemble the same global
        array as decode-everything-then-put-serially."""
        ref = self._batches(self._pipeline(ctx, mesh, wds_tar,
                                           decode_to_slot=False,
                                           decode_overlap_put=False))
        got = self._batches(self._pipeline(ctx, mesh, wds_tar,
                                           decode_to_slot=True,
                                           decode_overlap_put=True))
        for (ri, rl), (gi, gl) in zip(ref, got):
            np.testing.assert_array_equal(ri, gi)
            np.testing.assert_array_equal(rl, gl)

    def test_slot_without_overlap_matches_stack(self, ctx, mesh, wds_tar):
        ref = self._batches(self._pipeline(ctx, mesh, wds_tar,
                                           decode_to_slot=False,
                                           decode_overlap_put=False))
        got = self._batches(self._pipeline(ctx, mesh, wds_tar,
                                           decode_to_slot=True,
                                           decode_overlap_put=False))
        for (ri, rl), (gi, gl) in zip(ref, got):
            np.testing.assert_array_equal(ri, gi)
            np.testing.assert_array_equal(rl, gl)

    def test_replicated_sharding_overlap(self, ctx, wds_tar):
        """Fully-replicated batch: every device owns every row (overlapping
        groups), the hardest completion-accounting case."""
        mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        sharding = NamedSharding(mesh, P(None, None, None, None))
        from strom.pipelines import make_wds_vision_pipeline

        def build(**kw):
            return make_wds_vision_pipeline(
                ctx, [wds_tar], batch=4, image_size=32, sharding=sharding,
                shuffle=False, decode_workers=2, seed=5, **kw)

        ref = self._batches(build(decode_to_slot=False,
                                  decode_overlap_put=False), n=1)
        got = self._batches(build(decode_overlap_put=True), n=1)
        np.testing.assert_array_equal(ref[0][0], got[0][0])

    def test_slot_bytes_counter_and_stats_surface(self, ctx, mesh, wds_tar):
        before = global_stats.counter("decode_slot_bytes").value
        self._batches(self._pipeline(ctx, mesh, wds_tar), n=1)
        assert global_stats.counter("decode_slot_bytes").value > before
        dec = ctx.stats()["decode"]
        assert dec["decode_slot_bytes"] > 0
        assert dec["decode_batch_count"] > 0
        # the decode section rides the same Prometheus exposition as the
        # engine counters
        from strom.utils.stats import sections_prometheus

        text = sections_prometheus(ctx.stats())
        assert "strom_decode_decode_slot_bytes" in text
        assert "strom_decode_decode_batch_us_bucket" in text

    def test_decode_errors_surfaced_on_pipeline(self, ctx, mesh,
                                                tmp_path_factory):
        """A corrupt member yields a zero image row and a counted error —
        the batch (and the run) survives."""
        from tests.test_formats import make_wds_shard

        rng = np.random.default_rng(13)
        td = tmp_path_factory.mktemp("decode_err")
        samples = []
        for i in range(8):
            blob = b"CORRUPT" * 64 if i == 3 else noise_jpeg(rng, 48, 48)
            samples.append((f"s{i:04d}", {"jpg": blob,
                                          "cls": str(i).encode()}))
        tar = str(td / "bad.tar")
        make_wds_shard(tar, samples)
        with self._pipeline(ctx, mesh, tar) as pipe:
            imgs, _ = next(pipe)
            imgs_np = np.asarray(imgs)
            # >= 1, not == 1: the prefetcher may already be decoding the
            # next epoch's batch (same corrupt sample) when we look
            assert pipe.decode_errors >= 1
        assert not imgs_np[3].any()          # substituted zero image
        assert imgs_np[2].any() and imgs_np[4].any()


# --------------------------------------------------- cv2 global state hygiene
class TestCv2ThreadRestore:
    def test_close_restores_thread_count(self):
        prev = cv2.getNumThreads()
        try:
            cv2.setNumThreads(3)
            pool = DecodePool(2)
            pool.close()
            assert cv2.getNumThreads() == 3
            pool.close()  # idempotent: a second close must not re-restore
        finally:
            cv2.setNumThreads(prev)
