"""Extent-aware gather planning (VERDICT.md missing #3 / SURVEY.md §2.1
"Extent resolver"): the FIEMAP map must actually change the chunk plan on
fragmented files, preserve the byte mapping exactly, and leave contiguous
files untouched."""

import numpy as np
import pytest

from strom.delivery.chunk_plan import plan_chunks
from strom.probe.fiemap import (FIEMAP_EXTENT_DELALLOC, Extent,
                                fragmentation)


def ext(logical, physical, length, flags=0):
    return Extent(logical, physical, length, flags)


def byte_map(chunks):
    """(file_off -> dest_off) for every byte, plus total length."""
    m = {}
    for _, off, doff, ln in chunks:
        for k in range(ln):
            assert off + k not in m, "overlapping plan"
            m[off + k] = doff + k
    return m


class TestPlanChunks:
    def test_single_extent_identity(self):
        chunks = [(0, 0, 0, 4096), (0, 8192, 4096, 4096)]
        assert plan_chunks(chunks, [ext(0, 1 << 20, 1 << 20)]) == chunks

    def test_no_reliable_extents_identity(self):
        chunks = [(0, 0, 0, 4096)]
        em = [ext(0, 0, 2048, FIEMAP_EXTENT_DELALLOC),
              ext(2048, 0, 2048, FIEMAP_EXTENT_DELALLOC)]
        assert plan_chunks(chunks, em) == chunks

    def test_fragmented_reorders_physically(self):
        # logical order 0,1,2 placed physically 2,0,1
        em = [ext(0, 8 << 20, 4096), ext(4096, 0, 4096),
              ext(8192, 4 << 20, 4096)]
        naive = [(0, 0, 0, 12288)]
        plan = plan_chunks(naive, em)
        assert plan != naive, "fragmented file must produce a different plan"
        assert plan == [(0, 4096, 4096, 4096),   # phys 0
                        (0, 8192, 8192, 4096),   # phys 4M
                        (0, 0, 0, 4096)]         # phys 8M
        assert byte_map(plan) == byte_map(naive)

    def test_contiguous_extents_coalesce_back(self):
        # two extents that happen to be physically adjacent: split then re-merged
        em = [ext(0, 1 << 20, 8192), ext(8192, (1 << 20) + 8192, 8192)]
        naive = [(0, 0, 0, 16384)]
        assert plan_chunks(naive, em) == naive

    def test_holes_go_last_in_logical_order(self):
        em = [ext(0, 8 << 20, 4096), ext(8192, 0, 4096)]  # hole at [4096,8192)
        plan = plan_chunks([(0, 0, 0, 12288)], em)
        assert plan[-1] == (0, 4096, 4096, 4096)  # unmapped bytes last
        assert byte_map(plan) == byte_map([(0, 0, 0, 12288)])

    def test_property_random_maps_preserve_bytes(self):
        rng = np.random.default_rng(7)
        for _ in range(50):
            # random extent map over [0, 64KiB) in 4KiB grains
            grains = 16
            n_ext = int(rng.integers(1, 8))
            bounds = sorted(rng.choice(grains, size=n_ext - 1, replace=False)) \
                if n_ext > 1 else []
            bounds = [0] + [int(b) for b in bounds] + [grains]
            phys = rng.permutation(n_ext)
            em = []
            for i in range(n_ext):
                lo, hi = bounds[i] * 4096, bounds[i + 1] * 4096
                if hi > lo and rng.random() > 0.2:  # 20% chance: hole
                    em.append(ext(lo, int(phys[i]) * (1 << 20), hi - lo))
            # random non-overlapping chunks
            chunks = []
            pos, doff = 0, 0
            while pos < grains * 4096:
                ln = int(rng.integers(1, 5)) * 4096
                ln = min(ln, grains * 4096 - pos)
                if rng.random() > 0.3:
                    chunks.append((0, pos, doff, ln))
                    doff += ln
                pos += ln
            plan = plan_chunks(chunks, em)
            assert byte_map(plan) == byte_map(chunks)

    def test_chunk_spanning_before_first_extent(self):
        em = [ext(8192, 0, 4096), ext(16384, 1 << 20, 4096)]
        plan = plan_chunks([(0, 0, 0, 20480)], em)
        assert byte_map(plan) == byte_map([(0, 0, 0, 20480)])


class TestFragmentation:
    def test_contiguous(self):
        n, mean, seq = fragmentation([ext(0, 0, 4096), ext(4096, 4096, 4096)])
        assert (n, seq) == (2, 1.0) and mean == 4096

    def test_scattered(self):
        n, mean, seq = fragmentation([ext(0, 8 << 20, 4096), ext(4096, 0, 4096)])
        assert (n, seq) == (2, 0.0)

    def test_empty(self):
        assert fragmentation([]) == (0, 0, 1.0)


class TestDeliveryIntegration:
    def test_fragmented_map_reorders_and_reads_correctly(self, tmp_path,
                                                         monkeypatch):
        """With a (synthetic) fragmented extent map, delivery must submit a
        different chunk plan AND still return golden bytes — order changes,
        bytes don't."""
        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        path = str(tmp_path / "frag.bin")
        rng = np.random.default_rng(3)
        golden = rng.integers(0, 256, size=256 * 1024, dtype=np.uint8)
        with open(path, "wb") as f:
            f.write(golden.tobytes())

        # pretend the file is 4 extents laid out physically in reverse
        em = [ext(i * 65536, (3 - i) << 20, 65536) for i in range(4)]
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8))
        try:
            monkeypatch.setattr(ctx, "extent_map", lambda p: em)
            seen = []
            orig = ctx.engine.read_vectored

            def spy(chunks, dest, **kw):
                seen.append(list(chunks))
                return orig(chunks, dest, **kw)

            monkeypatch.setattr(ctx.engine, "read_vectored", spy)
            out = ctx.pread(path, length=256 * 1024)
            np.testing.assert_array_equal(out, golden)
            assert seen, "spy never saw a gather"
            offs = [off for (_, off, _, _) in seen[0]]
            assert offs == sorted(offs, reverse=True), \
                "reverse-physical layout should submit in reverse file order"
        finally:
            ctx.close()

    def test_streamed_transfer_with_fragmented_map(self, tmp_path,
                                                   monkeypatch):
        """Extent-aware planning applies PER STREAMED PIECE (each piece's
        _read_segments plans independently); a fragmented map must not
        corrupt a multi-piece streamed delivery."""
        import jax

        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        path = str(tmp_path / "big.bin")
        rng = np.random.default_rng(9)
        size = 1 << 20
        golden = rng.integers(0, 256, size=size, dtype=np.uint8)
        with open(path, "wb") as f:
            f.write(golden.tobytes())
        # 8 extents of 128KiB laid out physically in reverse
        em = [ext(i << 17, (7 - i) << 21, 1 << 17) for i in range(8)]
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8,
                                       overlap_chunk_bytes=256 * 1024,
                                       overlap_min_bytes=256 * 1024))
        try:
            monkeypatch.setattr(ctx, "extent_map", lambda p: em)
            seen = []
            orig = ctx.engine.read_vectored

            def spy(chunks, dest, **kw):
                seen.append(list(chunks))
                return orig(chunks, dest, **kw)

            monkeypatch.setattr(ctx.engine, "read_vectored", spy)
            arr = ctx.memcpy_ssd2tpu(path, length=size,
                                     device=jax.devices()[0])
            np.testing.assert_array_equal(np.asarray(arr), golden)
            # planning must have run inside EVERY piece: with the extents
            # physically reversed, each 256KiB piece's two 128KiB chunks
            # submit in reverse file order
            assert len(seen) >= 4, "expected one gather per streamed piece"
            for chunks in seen:
                offs = [off for (_, off, _, _) in chunks]
                assert offs == sorted(offs, reverse=True), chunks
        finally:
            ctx.close()

    def test_extent_map_cached(self, tmp_path):
        import importlib

        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        # strom.probe re-exports the fiemap FUNCTION under the same name as
        # the module, and `import a.b as x` resolves via package attribute —
        # go through importlib to get the module itself
        fmod = importlib.import_module("strom.probe.fiemap")

        path = str(tmp_path / "c.bin")
        with open(path, "wb") as f:
            f.write(b"x" * 8192)
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8))
        try:
            calls = []
            orig = fmod.fiemap

            def counting(p, *a, **kw):
                calls.append(p)
                return orig(p, *a, **kw)

            fmod.fiemap, saved = counting, orig
            try:
                ctx.extent_map(path)
                ctx.extent_map(path)
            finally:
                fmod.fiemap = saved
            assert len(calls) == 1, "FIEMAP must be probed once per file"
        finally:
            ctx.close()


class TestCheckFileAdvice:
    def test_fragmented_flag(self, tmp_path, monkeypatch):
        from strom.probe import check as cmod

        path = str(tmp_path / "f.bin")
        with open(path, "wb") as f:
            f.write(b"y" * 16384)
        em = [ext(0, 8 << 20, 8192), ext(8192, 0, 8192)]
        monkeypatch.setattr(cmod._fiemap, "fiemap", lambda p: em)
        rep = cmod.check_file(path)
        assert rep.fragmented
        assert rep.mean_extent_bytes == 8192
        assert any("fragmented" in r for r in rep.reasons)

    def test_real_file_not_flagged_when_contiguous(self, tmp_path):
        from strom.probe.check import check_file

        path = str(tmp_path / "small.bin")
        with open(path, "wb") as f:
            f.write(b"z" * 4096)
        rep = check_file(path)  # small files are contiguous (or unmapped)
        if rep.extents <= 1:
            assert not rep.fragmented


class TestPlanChunksMulti:
    def test_groups_by_file_first_appearance(self):
        from strom.delivery.chunk_plan import plan_chunks_multi

        chunks = [(2, 0, 0, 512), (1, 0, 512, 512), (2, 512, 1024, 512),
                  (1, 512, 1536, 512)]
        out = plan_chunks_multi(chunks, {})
        assert out == [(2, 0, 0, 512), (2, 512, 1024, 512),
                       (1, 0, 512, 512), (1, 512, 1536, 512)]

    def test_per_file_maps_reorder_only_their_file(self):
        from strom.delivery.chunk_plan import plan_chunks_multi

        # file 0 fragmented (physical order reversed), file 1 unmapped
        em0 = [ext(0, 1 << 20, 4096), ext(4096, 0, 4096)]
        chunks = [(0, 0, 0, 8192), (1, 0, 8192, 4096)]
        out = plan_chunks_multi(chunks, {0: em0})
        assert out == [(0, 4096, 4096, 4096), (0, 0, 0, 4096),
                       (1, 0, 8192, 4096)]

    def test_multi_file_byte_map_preserved(self):
        from strom.delivery.chunk_plan import plan_chunks_multi

        rng = np.random.default_rng(11)
        for _ in range(25):
            chunks = []
            doff = 0
            for fi in range(3):
                pos = 0
                for _ in range(int(rng.integers(1, 5))):
                    ln = int(rng.integers(1, 4)) * 4096
                    chunks.append((fi, pos, doff, ln))
                    pos += ln + int(rng.integers(0, 2)) * 4096
                    doff += ln
            rng.shuffle(chunks)
            # rebuild dest offsets non-overlapping after the shuffle
            chunks = [(fi, off, i * 16384, ln)
                      for i, (fi, off, _, ln) in enumerate(chunks)]
            em = {0: [ext(0, 5 << 20, 1 << 20)],
                  2: [ext(0, 1 << 20, 8192), ext(8192, 0, 8192)]}

            def mf_map(cs):
                return {(fi, off + k): doff + k
                        for fi, off, doff, ln in cs for k in range(ln)}

            out = plan_chunks_multi(chunks, em)
            assert mf_map(out) == mf_map(chunks)
