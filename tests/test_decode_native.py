"""Decode path v2 (ISSUE 12 tentpole): native libjpeg-turbo binding parity
against the cv2 path (bit-exact for full/reduced decode, bit-exact interior
for ROI), progressive (SOF2) routing, fused-run dispatch, the decoded-output
cache, span gating with telemetry off, and the build-probe fallback on a
host without usable libjpeg-turbo headers."""

import os
import subprocess
import sys

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from strom.formats import jpeg as J
from strom.formats.jpeg import (DECODE2_FIELDS, DecodePool, decode_jpeg,
                                make_train_transform, parse_jpeg_dims,
                                parse_jpeg_info)
from strom.utils.stats import global_stats

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_native = pytest.mark.skipif(not J.native_available(),
                                  reason="native jpeg binding not built "
                                         "(no libjpeg-turbo headers)")


def enc(img, quality=90, progressive=False):
    flags = [cv2.IMWRITE_JPEG_QUALITY, quality]
    if progressive:
        flags += [cv2.IMWRITE_JPEG_PROGRESSIVE, 1]
    ok, buf = cv2.imencode(".jpg", img, flags)
    assert ok
    return buf.tobytes()


def noise(rng, h, w):
    return rng.integers(0, 256, (h, w, 3), dtype=np.uint8)


def cv2_rgb(data, reduced=1):
    flag = {1: cv2.IMREAD_COLOR, 2: cv2.IMREAD_REDUCED_COLOR_2,
            4: cv2.IMREAD_REDUCED_COLOR_4,
            8: cv2.IMREAD_REDUCED_COLOR_8}[reduced]
    img = cv2.imdecode(np.frombuffer(data, np.uint8), flag)
    return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)


def philox(seed, row):
    return np.random.Generator(np.random.Philox(key=[seed, row]))


# ------------------------------------------------- SOF info (progressive fix)
class TestParseInfo:
    def test_baseline_not_progressive(self):
        rng = np.random.default_rng(0)
        info = parse_jpeg_info(enc(noise(rng, 80, 100)))
        assert info == (80, 100, False)

    def test_progressive_flag_golden(self):
        """The ISSUE 12 satellite golden fixture: a progressive (SOF2)
        member must carry the flag — the ROI router branches on it, since
        partial-scanline decode silently yields WRONG pixels on multi-scan
        files (no error, just corrupt training data)."""
        rng = np.random.default_rng(1)
        data = enc(noise(rng, 120, 90), progressive=True)
        info = parse_jpeg_info(data)
        assert info is not None and info.progressive
        assert (info.h, info.w) == (120, 90)
        # the dims-only wrapper keeps its historical contract
        assert parse_jpeg_dims(data) == (120, 90)

    def test_non_jpeg_none(self):
        assert parse_jpeg_info(b"not a jpeg at all") is None
        rng = np.random.default_rng(2)
        ok, png = cv2.imencode(".png", noise(rng, 16, 16))
        assert parse_jpeg_info(png.tobytes()) is None


# ------------------------------------------------------ native decode parity
@needs_native
class TestNativeParity:
    @pytest.mark.parametrize("h,w", [(64, 64), (201, 317), (448, 448),
                                     (95, 101)])
    def test_full_decode_bit_exact(self, h, w):
        rng = np.random.default_rng(h * w)
        data = enc(noise(rng, h, w))
        np.testing.assert_array_equal(J.decode_native(data), cv2_rgb(data))

    def test_grayscale_bit_exact(self):
        rng = np.random.default_rng(9)
        gray = rng.integers(0, 256, (70, 90), dtype=np.uint8)
        ok, buf = cv2.imencode(".jpg", gray, [cv2.IMWRITE_JPEG_QUALITY, 90])
        data = buf.tobytes()
        np.testing.assert_array_equal(J.decode_native(data), cv2_rgb(data))

    @pytest.mark.parametrize("d", [2, 4, 8])
    def test_reduced_bit_exact(self, d):
        rng = np.random.default_rng(d)
        data = enc(noise(rng, 403, 321))
        np.testing.assert_array_equal(J.decode_native(data, reduced=d),
                                      cv2_rgb(data, reduced=d))

    def test_out_param(self):
        rng = np.random.default_rng(11)
        data = enc(noise(rng, 60, 80))
        out = np.empty((60, 80, 3), np.uint8)
        got = J.decode_native(data, out=out)
        assert got is out
        np.testing.assert_array_equal(out, cv2_rgb(data))
        with pytest.raises(ValueError):
            J.decode_native(data, out=np.empty((59, 80, 3), np.uint8))

    @pytest.mark.parametrize("y,x,h,w", [
        (37, 53, 120, 200),   # interior rect
        (0, 0, 400, 600),     # whole frame as an ROI
        (0, 0, 16, 16),       # top-left corner
        (384, 584, 16, 16),   # bottom-right corner
        (100, 0, 50, 600),    # full-width band
        (0, 100, 400, 50),    # full-height band
        (399, 0, 1, 600),     # last row
    ])
    def test_roi_bit_exact_interior(self, y, x, h, w):
        """The returned rect (granted-edge columns excluded by the x
        margin) is bit-exact against a full decode — the property the
        transform-level parity rests on."""
        rng = np.random.default_rng(77)
        data = enc(noise(rng, 400, 600), quality=92)
        full = cv2_rgb(data)
        rect = J.decode_native(data, roi=(y, x, h, w))
        assert rect.shape == (h, w, 3)
        np.testing.assert_array_equal(rect, full[y: y + h, x: x + w])

    @pytest.mark.parametrize("d", [2, 4])
    def test_roi_composes_with_reduced(self, d):
        rng = np.random.default_rng(5)
        data = enc(noise(rng, 400, 600))
        full = cv2_rgb(data, reduced=d)
        rh, rw = full.shape[:2]
        y, x, h, w = rh // 4, rw // 4, rh // 2, rw // 2
        rect = J.decode_native(data, reduced=d, roi=(y, x, h, w))
        np.testing.assert_array_equal(rect, full[y: y + h, x: x + w])

    def test_roi_progressive_raises(self):
        """Defense in depth below the router: the C side refuses an ROI on
        a progressive source instead of returning wrong pixels."""
        rng = np.random.default_rng(6)
        data = enc(noise(rng, 128, 128), progressive=True)
        with pytest.raises(ValueError):
            J.decode_native(data, roi=(10, 10, 32, 32))
        # full decode of the same progressive member is fine and exact
        np.testing.assert_array_equal(J.decode_native(data), cv2_rgb(data))

    def test_roi_out_of_bounds_raises(self):
        rng = np.random.default_rng(7)
        data = enc(noise(rng, 64, 64))
        with pytest.raises(ValueError):
            J.decode_native(data, roi=(0, 0, 65, 64))

    def test_garbage_raises_valueerror(self):
        with pytest.raises(ValueError):
            J.decode_native(b"\xff\xd8definitely not entropy data")
        with pytest.raises(ValueError):
            J.decode_native(b"no soi marker here whatsoever")


# ------------------------------------------------- transform-level parity
@needs_native
class TestTransformV2:
    def _data(self, h=448, w=448, seed=3):
        rng = np.random.default_rng(seed)
        return enc(noise(rng, h, w))

    def test_native_matches_cv2_path_bit_exact(self):
        data = self._data()
        tf_old = make_train_transform(224, native=False, roi=False)
        tf_nat = make_train_transform(224, native=True, roi=False)
        for seed in range(8):
            ra, rb = philox(1, seed), philox(1, seed)
            np.testing.assert_array_equal(tf_old(data, ra), tf_nat(data, rb))
            # identical RNG consumption: checkpoint-resume determinism
            # does not depend on the knob
            assert ra.random() == rb.random()

    def test_roi_matches_full_path_bit_exact(self):
        data = self._data()
        tf_old = make_train_transform(224, native=False, roi=False)
        tf_roi = make_train_transform(224, native=True, roi=True)
        hits0 = global_stats.counter("decode_roi_hits").value
        rows0 = global_stats.counter("decode_roi_rows_skipped").value
        for seed in range(8):
            ra, rb = philox(2, seed), philox(2, seed)
            np.testing.assert_array_equal(tf_old(data, ra), tf_roi(data, rb))
            assert ra.random() == rb.random()
        assert global_stats.counter("decode_roi_hits").value > hits0
        assert global_stats.counter("decode_roi_rows_skipped").value > rows0

    def test_roi_composed_with_reduced_within_tolerance(self):
        """A high-res source engages reduced_denom AND the ROI on the
        reduced plane; parity vs the (reduced, non-ROI) path is bit-exact,
        and vs full-scale stays within the established codec tolerance."""
        rng = np.random.default_rng(21)
        # smooth gradient: near-lossless encode, same reasoning as
        # test_decode.smooth_jpeg
        yy, xx = np.mgrid[0:1024, 0:1024]
        img = np.stack([yy * 255 // 1023, xx * 255 // 1023,
                        (yy + xx) * 255 // 2046], axis=-1).astype(np.uint8)
        data = enc(img, quality=95)
        tf_red = make_train_transform(64, native=False, roi=False)
        tf_roi = make_train_transform(64, native=True, roi=True)
        red_hits0 = sum(global_stats.counter(f"decode_reduced_hits_{d}").value
                        for d in (2, 4, 8))
        for seed in range(4):
            ra, rb = philox(3, seed), philox(3, seed)
            a, b = tf_red(data, ra), tf_roi(data, rb)
            np.testing.assert_array_equal(a, b)
            assert ra.random() == rb.random()
        # the reduced path actually engaged under ROI
        assert sum(global_stats.counter(f"decode_reduced_hits_{d}").value
                   for d in (2, 4, 8)) > red_hits0

    def test_progressive_member_routed_to_full_decode(self):
        rng = np.random.default_rng(8)
        data = enc(noise(rng, 300, 300), progressive=True)
        tf_old = make_train_transform(128, native=False, roi=False)
        tf_roi = make_train_transform(128, native=True, roi=True)
        hits0 = global_stats.counter("decode_roi_hits").value
        for seed in range(4):
            ra, rb = philox(4, seed), philox(4, seed)
            np.testing.assert_array_equal(tf_old(data, ra), tf_roi(data, rb))
        # ROI never engaged on the progressive member
        assert global_stats.counter("decode_roi_hits").value == hits0


# ---------------------------------------------------------- fused dispatch
class TestFusedDispatch:
    def _blobs(self, n=12):
        rng = np.random.default_rng(13)
        return [enc(noise(rng, 80 + 8 * i, 100)) for i in range(n)]

    def test_run_size_rules(self):
        with DecodePool(2, fuse_runs=False) as p:
            assert p.run_size(64) == 1
        with DecodePool(2, fuse_runs=True) as p:
            assert p.run_size(1) == 1
            p._img_us = 50.0  # fast images -> want big runs
            # balance cap: every worker still sees >= 2 runs
            assert p.run_size(64) == -(-64 // (p.workers * 2))
            p._img_us = 1e6   # slow images -> no fusing worth it
            assert p.run_size(64) == 1

    def test_fused_map_into_bit_identical(self):
        blobs = self._blobs()
        tf = make_train_transform(32, native=False)
        ref = np.empty((12, 32, 32, 3), np.uint8)
        out = np.empty((12, 32, 32, 3), np.uint8)
        with DecodePool(3, fuse_runs=False) as p:
            p.map_into(tf, blobs, [philox(5, i) for i in range(12)], ref)
        runs0 = global_stats.counter("decode_fused_runs").value
        with DecodePool(3, fuse_runs=True) as p:
            p._img_us = 50.0  # force fusing regardless of host speed
            assert p.run_size(12) > 1
            p.map_into(tf, blobs, [philox(5, i) for i in range(12)], out)
        np.testing.assert_array_equal(ref, out)
        assert global_stats.counter("decode_fused_runs").value > runs0

    def test_fused_run_error_policy_per_sample(self):
        blobs = self._blobs(6)
        blobs[2] = b"definitely not a jpeg"
        tf = make_train_transform(16, native=False)
        before = global_stats.counter("decode_errors").value
        with DecodePool(2, fuse_runs=True) as p:
            p._img_us = 50.0
            out = np.full((6, 16, 16, 3), 255, np.uint8)
            p.map_into(tf, blobs, [philox(6, i) for i in range(6)], out)
            assert p.decode_errors == 1
        assert not out[2].any()          # bad row zeroed
        assert out[1].any() and out[3].any()  # run neighbors decoded
        assert global_stats.counter("decode_errors").value == before + 1

    def test_run_timing_feeds_ewma(self):
        blobs = self._blobs(8)
        tf = make_train_transform(32, native=False)
        with DecodePool(2, fuse_runs=True) as p:
            p._img_us = 1e9  # run 1: absurd seed, corrected by measurement
            p.map_into(tf, blobs, [philox(7, i) for i in range(8)],
                       np.empty((8, 32, 32, 3), np.uint8))
            # wait: run_size==1 path uses submit_into (no EWMA update);
            # drive a fused run explicitly
            p._img_us = 50.0
            p.map_into(tf, blobs, [philox(7, i) for i in range(8)],
                       np.empty((8, 32, 32, 3), np.uint8))
            assert 0 < p._img_us < 1e6  # converged toward reality


# ------------------------------------------------ span gating (satellite)
class TestSpanGating:
    def test_no_ring_events_when_disabled(self):
        from strom.obs.events import ring

        blobs = [enc(noise(np.random.default_rng(15), 40, 40))]
        tf = make_train_transform(16, native=False)
        prev = ring.enabled
        ring.enabled = False
        try:
            assert DecodePool._worker_span(None) is None
            n0 = ring.events_written
            with DecodePool(1) as p:
                p.map_into(tf, blobs, [philox(8, 0)],
                           np.empty((1, 16, 16, 3), np.uint8))
            assert ring.events_written == n0
        finally:
            ring.enabled = prev
        # enabled again: the decode span flows as before
        if prev:
            n0 = ring.events_written
            with DecodePool(1) as p:
                p.map_into(tf, blobs, [philox(8, 0)],
                           np.empty((1, 16, 16, 3), np.uint8))
            assert ring.events_written > n0


# ------------------------------------------------------ decoded-output cache
class TestDecodedCache:
    def _cache(self, mb=8):
        from strom.delivery.hotcache import HotCache

        return HotCache(mb * 1024 * 1024, admit="always")

    def test_roundtrip_and_counters(self):
        from strom.formats.decoded_cache import DecodedCache

        hc = self._cache()
        dc = DecodedCache(hc, fingerprint="rgb8/test")
        rng = np.random.default_rng(17)
        img = noise(rng, 50, 60)
        key = dc.key("/data/shard.tar", 1024, 9999)
        assert dc.get(key, 50, 60) is None
        assert dc.misses == 1
        assert dc.offer(key, img) == img.nbytes
        got = dc.get(key, 50, 60)
        assert got is not None
        view, pin = got
        np.testing.assert_array_equal(view, img)
        assert pin.refs == 1  # pinned for the crop+resize window
        dc.release(pin)
        assert pin.refs == 0
        assert dc.hits == 1 and dc.hit_bytes == img.nbytes

    def test_fingerprint_splits_keys(self):
        from strom.formats.decoded_cache import DecodedCache

        hc = self._cache()
        a = DecodedCache(hc, fingerprint="rgb8/turbo")
        b = DecodedCache(hc, fingerprint="rgb8/cv2")
        img = noise(np.random.default_rng(18), 20, 20)
        a.offer(a.key("s.tar", 0, 100), img)
        assert b.get(b.key("s.tar", 0, 100), 20, 20) is None

    def test_disabled_cache_serves_nothing(self):
        from strom.formats.decoded_cache import DecodedCache

        hc = self._cache()
        hc.enabled = False
        dc = DecodedCache(hc)
        assert not dc.enabled

    def test_tenant_partition_bounds_decoded_set(self):
        """Decoded frames charge the owning tenant's partition (ISSUE 7
        composition): a tenant at its cap self-evicts its own decoded
        entries and can never displace another tenant's."""
        from strom.formats.decoded_cache import DecodedCache

        hc = self._cache(64)
        img = noise(np.random.default_rng(19), 128, 128)  # 48KiB
        charge = hc._charge(img.nbytes)
        hc.set_partition("t1", 2 * charge)
        dc = DecodedCache(hc, tenant="t1")
        for i in range(4):
            dc.offer(dc.key("s.tar", i * 1000, i * 1000 + 500), img)
        parts = hc.partitions()
        assert parts["t1"]["bytes"] <= 2 * charge


# --------------------------------------------- pipeline-level decode cache
@pytest.fixture(scope="module")
def vision_setup(tmp_path_factory):
    import io
    import tarfile

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from strom.parallel.mesh import make_mesh

    rng = np.random.default_rng(23)
    td = tmp_path_factory.mktemp("decode2_wds")
    p = str(td / "shard.tar")
    with tarfile.open(p, "w") as tf:
        for i in range(16):
            blob = enc(noise(rng, 64 + 4 * i, 80))
            for name, data in ((f"s{i:04d}.jpg", blob),
                               (f"s{i:04d}.cls", str(i % 10).encode())):
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    return p, NamedSharding(mesh, P("dp", None, None, None))


class TestPipelineDecodeCache:
    def _batches(self, ctx, tar, sharding, n=4, **kw):
        from strom.pipelines import make_wds_vision_pipeline

        out = []
        with make_wds_vision_pipeline(
                ctx, [tar], batch=8, image_size=32, sharding=sharding,
                shuffle=False, decode_workers=2, seed=5, **kw) as pipe:
            for _ in range(n):
                imgs, lbls = next(pipe)
                out.append((np.asarray(imgs).copy(),
                            np.asarray(lbls).copy()))
        return out

    def test_cache_on_bit_identical_and_serves_epoch2(self, vision_setup):
        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        tar, sharding = vision_setup
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8,
                                       hot_cache_bytes=64 * 1024 * 1024,
                                       hot_cache_admit="always"))
        try:
            # reduced off on both sides: the cached path serves full-
            # fidelity pixels, so bit-identity holds against the
            # full-decode path (the reduced path is an approximation)
            ref = self._batches(ctx, tar, sharding,
                                decode_reduced_scale=False,
                                decode_cache=False)
            h0 = global_stats.counter("decode_cache_hits").value
            a0 = global_stats.counter("decode_cache_admitted_bytes").value
            got = self._batches(ctx, tar, sharding,
                                decode_reduced_scale=False,
                                decode_cache=True)
            for (ri, rl), (gi, gl) in zip(ref, got):
                np.testing.assert_array_equal(ri, gi)
                np.testing.assert_array_equal(rl, gl)
            # 4 batches x 8 rows over a 16-sample set = 2 epochs: epoch 1
            # admits, epoch 2 serves decoded pixels from RAM
            assert global_stats.counter(
                "decode_cache_admitted_bytes").value > a0
            assert global_stats.counter("decode_cache_hits").value >= h0 + 16
        finally:
            ctx.close()

    def test_plan_probe_skips_image_gather(self, vision_setup):
        """Decoded-cache fast path (ISSUE 13 satellite): epoch >= 2 probes
        the cache BEFORE extent planning — hit samples never gather their
        image member (labels + misses only), batches stay bit-identical to
        the full-gather path, and the gathered-byte counter collapses."""
        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        tar, sharding = vision_setup
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8,
                                       hot_cache_bytes=64 * 1024 * 1024,
                                       hot_cache_admit="always"))
        try:
            ref = self._batches(ctx, tar, sharding,
                                decode_reduced_scale=False,
                                decode_cache=False)
            ph0 = global_stats.counter("decode_cache_plan_hits").value
            ssd0 = global_stats.counter("ssd2tpu_bytes").value
            got = self._batches(ctx, tar, sharding,
                                decode_reduced_scale=False,
                                decode_cache=True)
            for (ri, rl), (gi, gl) in zip(ref, got):
                np.testing.assert_array_equal(ri, gi)
                np.testing.assert_array_equal(rl, gl)
            # 4 batches x 8 rows over 16 samples = 2 epochs: epoch 2's 16
            # rows (prefetch may run ahead) hit at PLAN time
            assert global_stats.counter(
                "decode_cache_plan_hits").value >= ph0 + 16
            assert global_stats.counter(
                "decode_cache_plan_skipped_bytes").value > 0
            # the cache-on pass gathered roughly half the bytes of the
            # cache-off pass (epoch 2 fetched labels only)
            cache_on_bytes = global_stats.counter("ssd2tpu_bytes").value \
                - ssd0
            full = sum(os.path.getsize(tar) for _ in (0,))
            assert cache_on_bytes < full * 2  # 4 batches ~ 2 epochs worth
        finally:
            ctx.close()

    def test_knobs_surface_in_stats_and_metrics(self, vision_setup):
        from strom.config import StromConfig
        from strom.delivery.core import StromContext
        from strom.utils.stats import sections_prometheus

        tar, sharding = vision_setup
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8))
        try:
            self._batches(ctx, tar, sharding, n=2)
            dec = ctx.stats(sections=["decode"])["decode"]
            for k in ("decode_native_imgs", "decode_fused_runs",
                      "decode_roi_hits", "decode_roi_rows_skipped",
                      "decode_cache_hits", "decode_cache_misses"):
                assert k in dec
            text = sections_prometheus(ctx.stats())
            assert "strom_decode_decode_fused_runs" in text
            assert "strom_decode_decode_roi_rows_skipped" in text
        finally:
            ctx.close()


# --------------------------------------------- build-probe fallback (subproc)
class TestBuildProbeFallback:
    def test_poisoned_include_path_falls_back_to_cv2(self, tmp_path):
        """ISSUE 12 satellite: on a host whose libjpeg-turbo headers are
        unusable, the engine still builds, import succeeds,
        ``decode_native is None``, and the cv2 decode path works. The
        poison is a shadowing jpeglib.h that #errors; the build lands in
        an isolated STROM_CORE_BUILD_DIR so the real .so is untouched."""
        poison = tmp_path / "poison"
        poison.mkdir()
        (poison / "jpeglib.h").write_text("#error poisoned include path\n")
        build = tmp_path / "build"
        env = dict(os.environ,
                   STROM_JPEG_CFLAGS=f"-I{poison}",
                   STROM_CORE_BUILD_DIR=str(build),
                   JAX_PLATFORMS="cpu")
        code = """
import numpy as np
from strom._core.build import ensure_built, jpeg_probe
assert jpeg_probe() is False, "poisoned probe must fail"
so = ensure_built()
import os
assert os.path.exists(so)
import ctypes
assert ctypes.CDLL(so).sc_jpeg_available() == 0
from strom.formats import jpeg as J
assert J.decode_native is None, "decode_native must resolve to None"
assert J.native_available() is False
# the cv2 path still decodes; the transform still works end to end
import cv2
img = np.random.default_rng(0).integers(0, 256, (64, 64, 3), dtype=np.uint8)
ok, buf = cv2.imencode(".jpg", img)
tf = J.make_train_transform(32, native=True, roi=True)  # knob on, lib absent
out = tf(buf.tobytes(), np.random.Generator(np.random.Philox(key=[0, 0])))
assert out.shape == (32, 32, 3)
print("FALLBACK_OK")
"""
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=300,
                              cwd=_ROOT)
        assert proc.returncode == 0, proc.stderr
        assert "FALLBACK_OK" in proc.stdout


# ----------------------------------------------------- field single-sourcing
def test_decode2_fields_are_counters_or_phase_keys():
    """Every DECODE2_FIELDS member is either a live global counter the
    decode path feeds or a rate/ratio the decode-v2 phase computes — the
    tuple is the single source the bench copy loop, compare_rounds and
    bench_sentinel all read."""
    phase_only = {"decode_native_img_per_s", "decode_cv2_img_per_s",
                  "decode_native_vs_cv2", "decode_cache_cold_img_per_s",
                  "decode_cache_warm_img_per_s",
                  "decode_cache_warm_vs_cold"}
    counters = set(DECODE2_FIELDS) - phase_only
    for k in counters:
        # touching the counter creates it if missing; the point is the
        # NAME is identical to what the producers feed (lint enforces the
        # near-duplicate half, this pins exact membership)
        assert global_stats.counter(k).value >= 0
