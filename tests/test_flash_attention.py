"""Pallas flash-attention kernel vs the dense oracle (interpret mode on the
CPU backend — same kernel code the TPU compiles)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from strom.ops.flash_attention import _dense_ref, flash_attention


def _qkv(rng, B, S, H, KV, Dh, dtype=jnp.float32):
    q = jnp.array(rng.normal(size=(B, S, H, Dh)), dtype)
    k = jnp.array(rng.normal(size=(B, S, KV, Dh)), dtype)
    v = jnp.array(rng.normal(size=(B, S, KV, Dh)), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("B,S,H,KV,Dh", [(2, 256, 4, 2, 128),
                                             (1, 256, 4, 4, 128)])
    def test_matches_dense(self, causal, B, S, H, KV, Dh):
        q, k, v = _qkv(np.random.default_rng(0), B, S, H, KV, Dh)
        out = flash_attention(q, k, v, causal)
        ref = _dense_ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_matches_model_attention(self):
        """Same semantics as the dense op the model uses."""
        from strom.models.llama import attention

        q, k, v = _qkv(np.random.default_rng(1), 1, 128, 4, 2, 128)
        out = flash_attention(q, k, v, True, 64, 64)
        ref = attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_blocked_vs_single_block(self):
        q, k, v = _qkv(np.random.default_rng(2), 1, 256, 2, 2, 128)
        a = flash_attention(q, k, v, True, 64, 128)
        b = flash_attention(q, k, v, True, 256, 256)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_matches_dense(self):
        q, k, v = _qkv(np.random.default_rng(3), 1, 128, 2, 2, 128)

        g1 = jax.grad(lambda q_: jnp.sum(flash_attention(q_, k, v) ** 2))(q)
        g2 = jax.grad(lambda q_: jnp.sum(_dense_ref(q_, k, v, True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("B,S,H,KV,Dh", [(2, 256, 4, 2, 128),   # GQA, 2x2 blocks
                                             (1, 128, 2, 2, 128)])  # MHA, 1 block
    def test_all_grads_match_dense(self, causal, B, S, H, KV, Dh):
        """The blockwise FA2 backward (dq AND dk AND dv kernels) against the
        dense oracle — the round-1 backward was a dense recompute, so this is
        the test that pins the new kernels down."""
        q, k, v = _qkv(np.random.default_rng(6), B, S, H, KV, Dh)

        def f_flash(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, causal) ** 2)

        def f_dense(q_, k_, v_):
            return jnp.sum(_dense_ref(q_, k_, v_, causal) ** 2)

        gq1, gk1, gv1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        gq2, gk2, gv2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in ((gq1, gq2, "dq"), (gk1, gk2, "dk"), (gv1, gv2, "dv")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4, err_msg=name)

    @pytest.mark.slow  # ~4s grid sweep: gradient parity vs dense is
    # covered (fast) above; tier-1 runtime headroom (ISSUE 5 satellite)
    def test_blocked_grads_vs_single_block(self):
        """Block-boundary accumulation in the backward: 64/128 blocking must
        reproduce the single-block result exactly (same math, different grid)."""
        q, k, v = _qkv(np.random.default_rng(7), 1, 256, 2, 2, 128)

        def loss(blocks):
            bq, bk = blocks
            return lambda q_, k_, v_: jnp.sum(
                flash_attention(q_, k_, v_, True, bq, bk) ** 2)

        g1 = jax.grad(loss((64, 128)), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss((256, 256)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)

    def test_grad_through_llama_loss(self):
        """End-to-end: next_token_loss gradient with the flash attn_fn is
        finite and close to the dense-path gradient."""
        from strom.models.llama import LlamaConfig, init_params, next_token_loss
        from strom.ops.flash_attention import make_flash_attention

        cfg = LlamaConfig(vocab=256, d_model=256, n_layers=2, n_heads=2,
                          n_kv_heads=2, d_ff=512, rope_theta=10_000.0)
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.array(np.random.default_rng(8).integers(0, 256, (1, 128)),
                           jnp.int32)
        attn = make_flash_attention(block_q=64, block_k=64)
        lf, gf = jax.value_and_grad(
            lambda p: next_token_loss(p, tokens, cfg, attn_fn=attn))(params)
        ld, gd = jax.value_and_grad(
            lambda p: next_token_loss(p, tokens, cfg))(params)
        assert np.isfinite(float(lf))
        assert abs(float(lf) - float(ld)) < 0.05
        # bf16 params/activations: gradients agree to bf16-noise scale
        flat_f = jax.tree_util.tree_leaves(gf)
        flat_d = jax.tree_util.tree_leaves(gd)
        for a, b in zip(flat_f, flat_d):
            a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
            denom = max(1e-3, float(np.abs(b).max()))
            assert float(np.abs(a - b).max()) / denom < 0.1

    def test_ragged_seq_rejected(self):
        q, k, v = _qkv(np.random.default_rng(4), 1, 100, 2, 2, 128)
        with pytest.raises(ValueError, match="must divide"):
            flash_attention(q, k, v, True, 64, 64)

    def test_plugs_into_llama_forward(self):
        from strom.models.llama import LlamaConfig, forward, init_params
        from strom.ops.flash_attention import make_flash_attention

        # head_dim 128 so the kernel tiles cleanly; 2 layers keep it fast
        cfg = LlamaConfig(vocab=256, d_model=256, n_layers=2, n_heads=2,
                          n_kv_heads=2, d_ff=512, rope_theta=10_000.0)
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.array(np.random.default_rng(5).integers(0, 256, (1, 128)),
                           jnp.int32)
        dense = forward(params, tokens, cfg)
        flash = forward(params, tokens, cfg,
                        attn_fn=make_flash_attention(block_q=64, block_k=64))
        # bf16 activations through 2 layers: compare at bf16-noise scale
        d, f = np.asarray(dense), np.asarray(flash)
        assert np.abs(d - f).max() < 0.15, np.abs(d - f).max()
        assert np.argmax(d[0, -1]) == np.argmax(f[0, -1])  # same prediction
