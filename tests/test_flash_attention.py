"""Pallas flash-attention kernel vs the dense oracle (interpret mode on the
CPU backend — same kernel code the TPU compiles)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from strom.ops.flash_attention import _dense_ref, flash_attention


def _qkv(rng, B, S, H, KV, Dh, dtype=jnp.float32):
    q = jnp.array(rng.normal(size=(B, S, H, Dh)), dtype)
    k = jnp.array(rng.normal(size=(B, S, KV, Dh)), dtype)
    v = jnp.array(rng.normal(size=(B, S, KV, Dh)), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("B,S,H,KV,Dh", [(2, 256, 4, 2, 128),
                                             (1, 256, 4, 4, 128)])
    def test_matches_dense(self, causal, B, S, H, KV, Dh):
        q, k, v = _qkv(np.random.default_rng(0), B, S, H, KV, Dh)
        out = flash_attention(q, k, v, causal)
        ref = _dense_ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_matches_model_attention(self):
        """Same semantics as the dense op the model uses."""
        from strom.models.llama import attention

        q, k, v = _qkv(np.random.default_rng(1), 1, 128, 4, 2, 128)
        out = flash_attention(q, k, v, True, 64, 64)
        ref = attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_blocked_vs_single_block(self):
        q, k, v = _qkv(np.random.default_rng(2), 1, 256, 2, 2, 128)
        a = flash_attention(q, k, v, True, 64, 128)
        b = flash_attention(q, k, v, True, 256, 256)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_matches_dense(self):
        q, k, v = _qkv(np.random.default_rng(3), 1, 128, 2, 2, 128)

        g1 = jax.grad(lambda q_: jnp.sum(flash_attention(q_, k, v) ** 2))(q)
        g2 = jax.grad(lambda q_: jnp.sum(_dense_ref(q_, k, v, True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-4)

    def test_ragged_seq_rejected(self):
        q, k, v = _qkv(np.random.default_rng(4), 1, 100, 2, 2, 128)
        with pytest.raises(ValueError, match="must divide"):
            flash_attention(q, k, v, True, 64, 64)

    def test_plugs_into_llama_forward(self):
        from strom.models.llama import LlamaConfig, forward, init_params
        from strom.ops.flash_attention import make_flash_attention

        # head_dim 128 so the kernel tiles cleanly; 2 layers keep it fast
        cfg = LlamaConfig(vocab=256, d_model=256, n_layers=2, n_heads=2,
                          n_kv_heads=2, d_ff=512, rope_theta=10_000.0)
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.array(np.random.default_rng(5).integers(0, 256, (1, 128)),
                           jnp.int32)
        dense = forward(params, tokens, cfg)
        flash = forward(params, tokens, cfg,
                        attn_fn=make_flash_attention(block_q=64, block_k=64))
        # bf16 activations through 2 layers: compare at bf16-noise scale
        d, f = np.asarray(dense), np.asarray(flash)
        assert np.abs(d - f).max() < 0.15, np.abs(d - f).max()
        assert np.argmax(d[0, -1]) == np.argmax(f[0, -1])  # same prediction
