"""GPipe-style pipeline parallelism (strom.parallel.pipeline): the pipelined
step must compute EXACTLY next_token_loss's function — same loss and same
gradients as the plain step — with layer stacks pp-sharded and activations
rotating via ppermute. Fake 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from strom.models.llama import LlamaConfig, init_params, next_token_loss
from strom.parallel.mesh import make_mesh
from strom.parallel.pipeline import make_pp_train_step
from strom.parallel.train import init_train_state, make_optimizer, make_train_step


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()  # 2 layers → pp=2


@pytest.fixture(scope="module")
def tokens(cfg):
    return jnp.array(np.random.default_rng(0).integers(0, cfg.vocab, (16, 32)),
                     jnp.int32)


@pytest.fixture(scope="module")
def ref_metrics(cfg, tokens):
    opt = make_optimizer()
    m1 = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    s1 = init_train_state(jax.random.PRNGKey(0), cfg, m1, opt)
    _, m = make_train_step(cfg, m1, opt, donate=False)(s1, tokens)
    return float(m["loss"]), float(m["grad_norm"])


class TestPipelineParallel:
    @pytest.mark.parametrize("axes,micro,attn", [
        ({"dp": 4, "pp": 2}, None, "dense"),   # default M = 2*pp
        ({"pp": 2}, 8, "dense"),               # pure pipeline, deep microbatching
        ({"dp": 2, "pp": 2}, 2, "dense"),      # minimal microbatching
        ({"dp": 4, "pp": 2}, 2, "flash"),      # Pallas kernel inside each stage
        ({"dp": 2, "tp": 2, "pp": 2}, 2, "dense"),  # manual tp inside the pipe
        ({"tp": 2, "pp": 2}, 4, "flash"),      # tp×pp with the flash kernel
        ({"dp": 2, "sp": 2, "pp": 2}, 2, "dense"),   # ring inside each stage
        ({"dp": 2, "sp": 2, "pp": 2}, 2, "flash"),   # flash ring in-pipe
        ({"dp": 2, "sp": 2, "pp": 2}, 2, "zigzag"),  # balanced ring in-pipe
        ({"tp": 2, "sp": 2, "pp": 2}, 2, "flash"),   # tp+sp+pp, flash ring
    ])
    def test_loss_and_grad_match_plain_step(self, cfg, tokens, ref_metrics,
                                            axes, micro, attn):
        ref_loss, ref_gn = ref_metrics
        n = 1
        for v in axes.values():
            n *= v
        mesh = make_mesh(axes, devices=jax.devices()[:n])
        opt = make_optimizer()
        state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
        step = make_pp_train_step(cfg, mesh, opt, donate=False,
                                  microbatches=micro, attn=attn)
        state, m = step(state, tokens)
        assert abs(float(m["loss"]) - ref_loss) < 2e-3, (axes, micro, attn)
        assert abs(float(m["grad_norm"]) - ref_gn) / ref_gn < 1e-3
        assert int(state.step) == 1

    def test_multiple_layers_per_stage(self):
        """4 layers over pp=2 → each stage scans 2 LOCAL layers; parity must
        hold for the stage-local scan, not just the 1-layer-per-stage case."""
        cfg4 = LlamaConfig(vocab=256, d_model=64, n_layers=4, n_heads=4,
                           n_kv_heads=2, d_ff=128, rope_theta=10_000.0)
        toks = jnp.array(
            np.random.default_rng(2).integers(0, cfg4.vocab, (8, 32)),
            jnp.int32)
        opt = make_optimizer()
        m1 = make_mesh({"dp": 1}, devices=jax.devices()[:1])
        s1 = init_train_state(jax.random.PRNGKey(1), cfg4, m1, opt)
        _, ref = make_train_step(cfg4, m1, opt, donate=False)(s1, toks)
        mesh = make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
        s2 = init_train_state(jax.random.PRNGKey(1), cfg4, mesh, opt)
        step = make_pp_train_step(cfg4, mesh, opt, donate=False,
                                  microbatches=2)
        _, m = step(s2, toks)
        assert abs(float(m["loss"]) - float(ref["loss"])) < 2e-3
        rel = abs(float(m["grad_norm"]) - float(ref["grad_norm"])) \
            / float(ref["grad_norm"])
        assert rel < 1e-3

    def test_pp_sharded_params(self, cfg):
        """The layer stacks actually live pp-sharded (n_layers/pp per stage)."""
        mesh = make_mesh({"dp": 4, "pp": 2}, devices=jax.devices()[:8])
        state = init_train_state(jax.random.PRNGKey(0), cfg,
                                 mesh, make_optimizer())
        wq = state.params["layers"]["wq"]
        assert wq.sharding.spec[0] == "pp"
        (shard,) = {s.data.shape for s in wq.addressable_shards
                    if s.index[0] == slice(0, 1)}
        assert shard[0] == cfg.n_layers // 2

    def test_rejects_bad_configs(self, cfg):
        opt = make_optimizer()
        with pytest.raises(ValueError, match="pp' mesh axis"):
            make_pp_train_step(cfg, make_mesh({"dp": 2},
                                              devices=jax.devices()[:2]), opt)
        with pytest.raises(ValueError, match="divide by tp"):
            # tiny has 2 kv heads: tp=4 can't split them
            make_pp_train_step(
                cfg, make_mesh({"tp": 4, "pp": 2}, devices=jax.devices()[:8]),
                opt)
        with pytest.raises(ValueError, match="divide by pp"):
            bad = LlamaConfig(vocab=64, d_model=32, n_layers=3, n_heads=2,
                              n_kv_heads=2, d_ff=64)
            make_pp_train_step(
                bad, make_mesh({"pp": 2}, devices=jax.devices()[:2]), opt)
        with pytest.raises(ValueError, match="microbatches"):
            make_pp_train_step(
                cfg, make_mesh({"pp": 2}, devices=jax.devices()[:2]), opt,
                microbatches=0)

    def test_microbatch_divisibility_surfaces(self, cfg, tokens):
        mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
        opt = make_optimizer()
        state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
        step = make_pp_train_step(cfg, mesh, opt, donate=False, microbatches=3)
        with pytest.raises(Exception, match="divide by"):
            step(state, tokens)  # 16 % 3 != 0

    def test_pipeline_feeds_from_loader(self, cfg, tmp_path):
        """End-to-end: packed-token delivery → pipelined step."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from strom.config import StromConfig
        from strom.delivery.core import StromContext
        from strom.pipelines import make_llama_pipeline

        mesh = make_mesh({"dp": 4, "pp": 2}, devices=jax.devices()[:8])
        path = str(tmp_path / "t.bin")
        np.random.default_rng(3).integers(0, cfg.vocab, 33 * 40,
                                          dtype=np.int32).tofile(path)
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8))
        try:
            opt = make_optimizer()
            state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
            step = make_pp_train_step(cfg, mesh, opt, microbatches=2)
            with make_llama_pipeline(ctx, [path], batch=8, seq_len=32,
                                     sharding=NamedSharding(mesh, P("dp", None))
                                     ) as pipe:
                state, m = step(state, next(pipe))
            assert np.isfinite(float(m["loss"]))
            assert int(state.step) == 1
        finally:
            ctx.close()
