"""Property tests (hypothesis) for the pure planning math: stripe decode,
extent location, sharded-segment decomposition, sampler coverage
(SURVEY.md §4.2 'Unit' row: "property tests")."""

import numpy as np
from hypothesis import given, settings, strategies as st

from strom.delivery.extents import ExtentList
from strom.delivery.shard import contiguous_segments
from strom.engine.raid0 import coalesce, plan_stripe_reads
from strom.pipelines.sampler import EpochShuffleSampler, SamplerState


class TestStripeProperties:
    @given(offset=st.integers(0, 1 << 20), length=st.integers(0, 1 << 18),
           n=st.integers(1, 8), chunk_pow=st.integers(9, 16))
    @settings(max_examples=200, deadline=None)
    def test_stripe_reassembles_identity(self, offset, length, n, chunk_pow):
        """Reading the planned member segments out of a striped 'disk' model
        must reproduce the logical range exactly."""
        chunk = 1 << chunk_pow
        segs = plan_stripe_reads(offset, length, n, chunk)
        # coverage: in logical order, no gaps/overlaps
        assert sum(s.length for s in segs) == length
        pos = offset
        for s in segs:
            assert s.logical_offset == pos
            pos += s.length
        # correctness of the member mapping: invert it
        for s in segs:
            for d in (0, s.length - 1) if s.length else ():
                logical = s.logical_offset + d
                member_byte = s.member_offset + d
                chunk_idx = logical // chunk
                assert s.member == chunk_idx % n
                assert member_byte == (chunk_idx // n) * chunk + logical % chunk

    @given(offset=st.integers(0, 1 << 16), length=st.integers(0, 1 << 16),
           n=st.integers(1, 4), chunk_pow=st.integers(9, 12))
    @settings(max_examples=100, deadline=None)
    def test_coalesce_preserves_bytes(self, offset, length, n, chunk_pow):
        segs = plan_stripe_reads(offset, length, n, 1 << chunk_pow)
        merged = coalesce(segs)
        assert sum(s.length for s in merged) == length
        assert len(merged) <= len(segs)


class TestExtentProperties:
    @given(st.data())
    @settings(max_examples=200, deadline=None)
    def test_locate_matches_materialized(self, data):
        """locate() over random extents == slicing the materialized stream."""
        n_ext = data.draw(st.integers(1, 8))
        exts, stream = [], []
        for i in range(n_ext):
            ln = data.draw(st.integers(1, 256))
            off = data.draw(st.integers(0, 1024))
            path = f"f{data.draw(st.integers(0, 2))}"
            exts.append((path, off, ln))
            stream.extend((path, off + j) for j in range(ln))
        el = ExtentList(exts)
        assert el.size == len(stream)
        lo = data.draw(st.integers(0, el.size))
        ln = data.draw(st.integers(0, el.size - lo))
        runs = list(el.locate(lo, ln, dest_offset=5))
        flat = [(r.path, r.offset + j) for r in runs for j in range(r.length)]
        assert flat == stream[lo: lo + ln]
        # dest offsets are contiguous from 5
        if runs:
            assert runs[0].dest_offset == 5
            for a, b in zip(runs, runs[1:]):
                assert b.dest_offset == a.dest_offset + a.length


class TestSegmentProperties:
    @given(st.data())
    @settings(max_examples=150, deadline=None)
    def test_segments_reconstruct_subblock(self, data):
        """contiguous_segments of a random rectangular sub-block must copy
        exactly the bytes numpy slicing produces."""
        ndim = data.draw(st.integers(1, 3))
        shape = tuple(data.draw(st.integers(1, 6)) for _ in range(ndim))
        itemsize = data.draw(st.sampled_from([1, 2, 4]))
        index = []
        for dim in shape:
            lo = data.draw(st.integers(0, dim - 1))
            hi = data.draw(st.integers(lo + 1, dim))
            index.append(slice(lo, hi))
        index = tuple(index)
        total = int(np.prod(shape)) * itemsize
        src = np.arange(total, dtype=np.uint8)
        arr = src.view(np.uint8).reshape(tuple(shape) + (itemsize,)) \
            if itemsize > 1 else src.reshape(shape)
        want = (arr[index].reshape(-1).tobytes() if itemsize == 1 else
                arr[index + (slice(None),)].reshape(-1).tobytes())
        segs = list(contiguous_segments(shape, itemsize, index))
        out = bytearray(len(want))
        for s in segs:
            out[s.dest_offset: s.dest_offset + s.length] = \
                src[s.file_offset: s.file_offset + s.length].tobytes()
        assert bytes(out) == want


class TestSamplerProperties:
    @given(num=st.integers(1, 500), batch_frac=st.integers(1, 100),
           seed=st.integers(0, 2**31))
    @settings(max_examples=100, deadline=None)
    def test_epoch_partition(self, num, batch_frac, seed):
        batch = max(1, min(num, batch_frac))
        s = EpochShuffleSampler(num, batch, seed=seed)
        it = iter(s)
        seen = np.concatenate([next(it) for _ in range(s.batches_per_epoch)])
        assert len(seen) == len(set(seen.tolist()))  # no duplicates
        assert set(seen.tolist()) <= set(range(num))

    @given(num=st.integers(2, 300), seed=st.integers(0, 2**31),
           advance=st.integers(0, 50))
    @settings(max_examples=100, deadline=None)
    def test_resume_exact(self, num, seed, advance):
        batch = max(1, num // 7)
        s1 = EpochShuffleSampler(num, batch, seed=seed)
        it1 = iter(s1)
        for _ in range(advance):
            next(it1)
        bpe = s1.batches_per_epoch
        s2 = EpochShuffleSampler(
            num, batch, seed=seed,
            state=SamplerState(epoch=advance // bpe,
                               batch_in_epoch=advance % bpe, seed=seed))
        np.testing.assert_array_equal(next(iter(s2)), next(it1))


class TestStripedAliasProperties:
    @given(n=st.integers(2, 5), chunk_pow=st.integers(9, 13),
           size_jitter=st.integers(0, 8191),
           ranges=st.lists(st.tuples(st.integers(0, 1 << 18),
                                     st.integers(1, 1 << 14)),
                           min_size=1, max_size=6))
    @settings(max_examples=30, deadline=None)
    def test_alias_extent_reads_match_golden(self, tmp_path_factory, n,
                                             chunk_pow, size_jitter, ranges):
        """End-to-end: stripe_file + register_striped + ExtentList gathers
        against the alias return exactly the bytes of the original file,
        for random stripe geometry and random (offset, length) extents."""
        from strom.config import StromConfig
        from strom.delivery.core import StromContext
        from strom.engine.raid0 import stripe_file

        chunk = 1 << chunk_pow
        td = tmp_path_factory.mktemp("alias")
        data = np.random.default_rng(n * chunk_pow).integers(
            0, 256, 3 * n * chunk + size_jitter, dtype=np.uint8)
        src = td / "src.bin"
        data.tofile(src)
        members = [str(td / f"m{i}.bin") for i in range(n)]
        true_size = stripe_file(str(src), members, chunk)
        assert true_size == len(data)
        virt = str(td / "virt.bin")
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8))
        try:
            ctx.register_striped(virt, members, chunk)
            exts, golden = [], []
            for off, ln in ranges:
                off = off % len(data)
                ln = min(ln, len(data) - off)
                if ln:
                    exts.append((virt, off, ln))
                    golden.append(data[off: off + ln])
            if exts:
                got = ctx.pread(ExtentList(exts))
                np.testing.assert_array_equal(got, np.concatenate(golden))
        finally:
            ctx.close()
