"""tools/lint_stats_names.py wired in as a tier-1 test: the REPO's own
global-stats namespace must be free of case/underscore near-duplicates
(a restyled metric name silently forks the series — producer feeds one
spelling, dashboards read the other), and the linter itself must actually
catch one."""

import importlib.util
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SPEC = importlib.util.spec_from_file_location(
    "lint_stats_names", os.path.join(_ROOT, "tools", "lint_stats_names.py"))
lint = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(lint)


def test_repo_is_clean():
    assert lint.main([_ROOT]) == 0


def test_repo_scan_finds_known_names():
    found, _labels = lint.scan_sources(_ROOT)
    # sanity: the scan actually sees the well-known counters, so a clean
    # result means "no collisions", not "nothing scanned"
    assert "ssd2tpubytes" in found
    assert "decodeerrors" in found


def test_collision_detected(tmp_path):
    pkg = tmp_path / "strom"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'global_stats.add("coalesce_ops_in", 1)\n'
        'global_stats.set_gauge("Coalesce_OpsIn", 2)\n')
    (pkg / "b.py").write_text(
        'global_stats.observe_us("read_latency", 3.0)\n')
    found, _labels = lint.scan_sources(str(tmp_path))
    bad = lint.collisions(found)
    assert len(bad) == 1
    (norm, uses) = bad[0]
    assert norm == "coalesceopsin"
    assert {lit for lit, _ in uses} == {"coalesce_ops_in", "Coalesce_OpsIn"}
    assert lint.main([str(tmp_path)]) == 1


def test_fields_tuple_literals_scanned(tmp_path):
    """Single-sourced name tuples (CACHE_BENCH_FIELDS, STALL_FIELDS, the
    compare_rounds *_KEYS lists) are part of the metric namespace: a
    restyled spelling there forks a dashboard column exactly like a
    restyled call site (ISSUE 4 satellite)."""
    pkg = tmp_path / "strom"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'CACHE_BENCH_FIELDS = (\n'
        '    "cache_hit_bytes",\n'
        '    "warm_images_per_s",\n'
        ')\n')
    (pkg / "b.py").write_text(
        'global_stats.add("Cache_HitBytes", 1)\n')
    found, _labels = lint.scan_sources(str(tmp_path))
    assert "warmimagespers" in found
    bad = lint.collisions(found)
    assert len(bad) == 1
    assert bad[0][0] == "cachehitbytes"
    assert lint.main([str(tmp_path)]) == 1


def test_repo_fields_tuples_seen():
    """The real repo scan picks up the single-sourced tuples (cache bench
    columns + stall fields), so 'clean' covers them too."""
    found, _labels = lint.scan_sources(_ROOT)
    assert "warmvscold" in found          # hotcache CACHE_BENCH_FIELDS
    assert "cachehitbytes" in found
    assert "goodputpct" in found          # stall STALL_FIELDS


def test_fstring_literals_scanned(tmp_path):
    pkg = tmp_path / "strom"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'global_stats.add(f"decode_reduced_hits_{denom}")\n')
    found, _labels = lint.scan_sources(str(tmp_path))
    assert any("decodereducedhits" in k for k in found)


def test_usage_error_on_missing_dir(tmp_path):
    assert lint.main([str(tmp_path / "nope")]) == 2


def test_scope_call_sites_scanned(tmp_path):
    """Writes through a threaded scope (self.scope / pscope / op_scope)
    land in the same aggregate namespace as global_stats calls, so the
    lint must see them — a restyled spelling through a scope forks the
    metric exactly the same way (ISSUE 6 satellite)."""
    pkg = tmp_path / "strom"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'self.scope.add("ssd2tpu_bytes", n)\n'
        'pscope.observe_us("decode_batch", us)\n'
        'self.op_scope.set_gauge("engine_inflight", d)\n')
    (pkg / "b.py").write_text(
        'global_stats.add("SSD2TPU_Bytes", 1)\n')
    found, _labels = lint.scan_sources(str(tmp_path))
    assert "decodebatch" in found
    assert "engineinflight" in found
    bad = lint.collisions(found)
    assert [norm for norm, _ in bad] == ["ssd2tpubytes"]
    assert lint.main([str(tmp_path)]) == 1


def test_scope_label_keys_linted(tmp_path):
    """.scoped() label KEYS are their own collision domain: `pipeline` vs
    `Pipe_Line` would fork every labeled series on /metrics."""
    pkg = tmp_path / "strom"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'ctx.scope.scoped(pipeline="resnet", tenant=name)\n')
    (pkg / "b.py").write_text(
        's = registry.scoped(Pipe_Line="vit")\n')
    _found, labels = lint.scan_sources(str(tmp_path))
    assert "tenant" in labels
    bad = lint.collisions(labels)
    assert [norm for norm, _ in bad] == ["pipeline"]
    assert lint.main([str(tmp_path)]) == 1


def test_repo_flight_and_sentinel_tuples_seen():
    """FLIGHT_FIELDS (strom/obs/flight.py) and SENTINEL_FIELDS
    (tools/bench_sentinel.py) ride the same *_FIELDS scan as the cache/
    stall tuples, so their spellings cannot fork from the producers."""
    found, labels = lint.scan_sources(_ROOT)
    assert "pipelinesteps" in found       # FLIGHT_FIELDS + Pipeline scope
    assert "ringeventsdropped" in found   # FLIGHT_FIELDS
    assert "trainwgoodputpct" not in found  # sanity: no phantom names
    assert "vsbaselinehost" in found      # SENTINEL_FIELDS via binding set
    # the repo actually uses scoped labels (pipeline=, tenant= in tests)
    assert "pipeline" in labels


def test_repo_decode2_tuple_seen():
    """DECODE2_FIELDS (strom/formats/jpeg.py) rides the *_FIELDS scan
    (ISSUE 12 satellite) so the decode-v2 bench columns, the
    compare_rounds section and the sentinel gates can't fork spellings
    from the counter producers."""
    found, _labels = lint.scan_sources(_ROOT)
    assert "decodenativeimgpers" in found     # DECODE2_FIELDS
    assert "decoderoirowsskipped" in found    # DECODE2_FIELDS + producer
    assert "decodecachewarmimgpers" in found  # DECODE2_FIELDS
    assert "decodefusedruns" in found         # DECODE2_FIELDS + producer


def test_repo_slo_and_exemplar_tuples_seen():
    """SLO_FIELDS / SLO_BENCH_FIELDS (strom/obs/slo.py) and
    EXEMPLAR_FIELDS (strom/obs/exemplars.py) ride the *_FIELDS scan
    (ISSUE 8 satellite) so the burn-rate gauges, bench columns and
    retention counters can't fork spellings from their producers."""
    found, _labels = lint.scan_sources(_ROOT)
    assert "sloburnfast" in found         # SLO_FIELDS
    assert "reqlatp99us" in found         # SLO_BENCH_FIELDS
    assert "exemplarsretained" in found   # EXEMPLAR_FIELDS + FLIGHT_FIELDS


def test_route_doc_lint_repo_clean():
    """Every do_GET/do_POST route literal in strom/obs/server.py must be
    documented in README.md (ISSUE 8 satellite) — and the scan must
    actually see the known routes, so clean means 'all documented', not
    'nothing scanned'."""
    routes, missing = lint.scan_routes(_ROOT)
    assert {"/metrics", "/stats", "/trace", "/tenants", "/flight",
            "/slo", "/history"} <= routes
    assert missing == []


def test_route_doc_lint_catches_undocumented(tmp_path):
    """An undocumented route fails the lint with a pointed message."""
    srv = tmp_path / "strom" / "obs"
    os.makedirs(srv)
    (srv / "server.py").write_text(
        'if path == "/metrics":\n    pass\n'
        'elif path == "/secret_route":\n    pass\n')
    (tmp_path / "README.md").write_text("only /metrics documented here\n")
    routes, missing = lint.scan_routes(str(tmp_path))
    assert routes == {"/metrics", "/secret_route"}
    assert missing == ["/secret_route"]
    assert lint.main([str(tmp_path)]) == 1
