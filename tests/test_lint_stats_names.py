"""tools/lint_stats_names.py wired in as a tier-1 test: the REPO's own
global-stats namespace must be free of case/underscore near-duplicates
(a restyled metric name silently forks the series — producer feeds one
spelling, dashboards read the other), and the linter itself must actually
catch one."""

import importlib.util
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SPEC = importlib.util.spec_from_file_location(
    "lint_stats_names", os.path.join(_ROOT, "tools", "lint_stats_names.py"))
lint = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(lint)


def test_repo_is_clean():
    assert lint.main([_ROOT]) == 0


def test_repo_scan_finds_known_names():
    found = lint.scan_sources(_ROOT)
    # sanity: the scan actually sees the well-known counters, so a clean
    # result means "no collisions", not "nothing scanned"
    assert "ssd2tpubytes" in found
    assert "decodeerrors" in found


def test_collision_detected(tmp_path):
    pkg = tmp_path / "strom"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'global_stats.add("coalesce_ops_in", 1)\n'
        'global_stats.set_gauge("Coalesce_OpsIn", 2)\n')
    (pkg / "b.py").write_text(
        'global_stats.observe_us("read_latency", 3.0)\n')
    found = lint.scan_sources(str(tmp_path))
    bad = lint.collisions(found)
    assert len(bad) == 1
    (norm, uses) = bad[0]
    assert norm == "coalesceopsin"
    assert {lit for lit, _ in uses} == {"coalesce_ops_in", "Coalesce_OpsIn"}
    assert lint.main([str(tmp_path)]) == 1


def test_fields_tuple_literals_scanned(tmp_path):
    """Single-sourced name tuples (CACHE_BENCH_FIELDS, STALL_FIELDS, the
    compare_rounds *_KEYS lists) are part of the metric namespace: a
    restyled spelling there forks a dashboard column exactly like a
    restyled call site (ISSUE 4 satellite)."""
    pkg = tmp_path / "strom"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'CACHE_BENCH_FIELDS = (\n'
        '    "cache_hit_bytes",\n'
        '    "warm_images_per_s",\n'
        ')\n')
    (pkg / "b.py").write_text(
        'global_stats.add("Cache_HitBytes", 1)\n')
    found = lint.scan_sources(str(tmp_path))
    assert "warmimagespers" in found
    bad = lint.collisions(found)
    assert len(bad) == 1
    assert bad[0][0] == "cachehitbytes"
    assert lint.main([str(tmp_path)]) == 1


def test_repo_fields_tuples_seen():
    """The real repo scan picks up the single-sourced tuples (cache bench
    columns + stall fields), so 'clean' covers them too."""
    found = lint.scan_sources(_ROOT)
    assert "warmvscold" in found          # hotcache CACHE_BENCH_FIELDS
    assert "cachehitbytes" in found
    assert "goodputpct" in found          # stall STALL_FIELDS


def test_fstring_literals_scanned(tmp_path):
    pkg = tmp_path / "strom"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'global_stats.add(f"decode_reduced_hits_{denom}")\n')
    found = lint.scan_sources(str(tmp_path))
    assert any("decodereducedhits" in k for k in found)


def test_usage_error_on_missing_dir(tmp_path):
    assert lint.main([str(tmp_path / "nope")]) == 2
