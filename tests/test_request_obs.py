"""Causal request tracing, exemplars, SLO engine, history, strom_top
(ISSUE 8 tentpole).

The acceptance scenario lives in TestAcceptance: a two-tenant run with a
deliberately slow/throttled gather must yield (a) a Perfetto-loadable
trace whose queue→grant→engine→decode→put spans all carry the request's
req_id with flow events connecting them, (b) that request's span tree in
the exemplar store while fast requests are discarded, and (c) /slo
reporting the burn with the throttled tenant flagged on /tenants — with
strom_top --once rendering the per-tenant table from the live server.
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from strom.config import StromConfig
from strom.obs import request as obs_request
from strom.obs.events import EventRing, ring as global_ring
from strom.obs.exemplars import ExemplarStore, store as global_store
from strom.obs.history import StatsHistory
from strom.obs.slo import SLO_BENCH_FIELDS, SLO_FIELDS, SloEngine, SloTarget

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeReq:
    """Duck-typed Request for store/SLO unit tests."""

    def __init__(self, tenant="t", kind="gather", dur_us=1000.0,
                 throttled=False, error=None, queue_wait_us=0.0):
        self.id = 1
        self.tenant = tenant
        self.kind = kind
        self.dur_us = dur_us
        self.throttled = throttled
        self.error = error
        self.queue_wait_us = queue_wait_us
        self.t0_us = 0.0
        self.spans_dropped = 0
        self.spans = []

    def to_doc(self):
        return {"req": self.id, "tenant": self.tenant, "kind": self.kind,
                "t0_us": self.t0_us, "dur_us": self.dur_us,
                "queue_wait_us": self.queue_wait_us,
                "throttled": self.throttled, "error": self.error,
                "spans_dropped": 0, "spans": list(self.spans)}


# --------------------------------------------------------------- ring flows
class TestFlowEvents:
    def test_flow_events_snapshot_and_export(self):
        ring = EventRing(capacity=64)
        ring.flow("s", 7, "req.gather", "req")
        ring.flow("t", 7, "req.gather", "req")
        ring.flow("f", 7, "req.gather", "req")
        snap = ring.snapshot()
        assert [e["ph"] for e in snap] == ["s", "t", "f"]
        assert all(e["id"] == 7 for e in snap)

        from strom.obs.chrome_trace import to_trace_events

        tes = to_trace_events(snap)
        assert [te["ph"] for te in tes] == ["s", "t", "f"]
        assert all(te["id"] == 7 for te in tes)
        # steps/ends bind to the enclosing slice; starts don't need bp
        assert "bp" not in tes[0] and tes[1]["bp"] == "e"

    def test_flow_events_roundtrip_through_file(self, tmp_path):
        from strom.obs import chrome_trace

        ring = EventRing(capacity=16)
        with ring.span("work", cat="read"):
            ring.flow("s", 3, "req.gather", "req")
        p = str(tmp_path / "t.json")
        chrome_trace.dump(p, ring=ring)
        back = chrome_trace.load_events(p)
        phs = {e["ph"] for e in back}
        assert phs == {"X", "s"}
        assert next(e for e in back if e["ph"] == "s")["id"] == 3

    def test_flow_events_invisible_to_stall_attribution(self):
        from strom.obs import stall

        ring = EventRing(capacity=16)
        ring.flow("s", 1, "req.x", "req")
        ring.complete(0.0, 100.0, "ingest_wait", "pipeline.next")
        assert stall.steps_summary(ring.snapshot())["steps_observed"] == 1


# ------------------------------------------------------------ request object
class TestRequest:
    def test_span_tree_parent_links_and_args(self):
        global_ring.clear()
        req = obs_request.Request("gather", "tx")
        with req.span("outer", cat="read"):
            with req.span("inner", cat="sched"):
                pass
        req.finish()
        names = {s[0]: s for s in req.spans}
        assert names["inner"][5] == "outer"      # parent link
        assert names["outer"][5] is None
        evs = [e for e in global_ring.snapshot() if e.get("ph") == "X"]
        by_name = {e["name"]: e for e in evs}
        assert by_name["inner"]["args"]["req"] == req.id
        assert by_name["inner"]["args"]["parent"] == "outer"
        flows = [e for e in global_ring.snapshot() if e.get("ph") in "st"]
        assert [e["ph"] for e in flows] == ["s", "t"]
        assert all(e["id"] == req.id for e in flows)

    def test_span_tree_bounded(self):
        req = obs_request.Request("gather")
        for i in range(obs_request.MAX_SPANS_PER_REQUEST + 10):
            req.record(f"s{i}", "read", 0.0, 1.0)
        assert len(req.spans) == obs_request.MAX_SPANS_PER_REQUEST
        assert req.spans_dropped == 10
        req.finish()

    def test_active_reuses_enclosing_request(self):
        with obs_request.active("batch", "t0") as outer:
            with obs_request.active("gather", "t0") as inner:
                assert inner is outer
            assert not outer._finished  # inner exit must not finish it
        assert outer._finished

    def test_finish_idempotent_and_observers(self):
        seen = []
        obs_request.add_observer(seen.append)
        try:
            with obs_request.active("gather", "t0"):
                pass
        finally:
            obs_request.remove_observer(seen.append)
        assert len(seen) == 1 and seen[0].tenant == "t0"

    def test_attach_propagates_across_threads(self):
        req = obs_request.Request("batch", "t0")
        got = []

        def worker():
            with obs_request.attach(req):
                got.append(obs_request.current())

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert got == [req]
        req.finish()

    def test_error_marked_on_exception(self):
        with pytest.raises(ValueError):
            with obs_request.active("gather", "terr") as req:
                raise ValueError("boom")
        assert req.error and "boom" in req.error


# ------------------------------------------------------------ exemplar store
class TestExemplarStore:
    def test_slow_retained_fast_discarded(self):
        st = ExemplarStore(per_tenant=4, min_window=8)
        for _ in range(20):
            assert not st.offer(FakeReq(dur_us=1000.0))
        assert st.offer(FakeReq(dur_us=50_000.0))  # above rolling p99
        snap = st.snapshot()
        assert snap["exemplars_retained"] == 1
        assert [e["dur_us"] for e in snap["tenants"]["t"]] == [50_000.0]

    def test_no_verdict_below_min_window(self):
        st = ExemplarStore(min_window=16)
        assert not st.offer(FakeReq(dur_us=10_000_000.0))  # cold store

    def test_throttled_and_errored_always_retained(self):
        st = ExemplarStore(min_window=16)
        assert st.offer(FakeReq(throttled=True))
        assert st.offer(FakeReq(error="EngineError: boom"))
        s = st.stats()
        assert s["exemplars_throttled"] == 1
        assert s["exemplars_errored"] == 1

    def test_windows_keyed_by_kind(self):
        st = ExemplarStore(min_window=8)
        for _ in range(10):   # slow "step" traffic must not define gather p99
            st.offer(FakeReq(kind="step", dur_us=1_000_000.0))
        for _ in range(10):
            st.offer(FakeReq(kind="gather", dur_us=100.0))
        assert st.offer(FakeReq(kind="gather", dur_us=5_000.0))

    def test_bounded_per_tenant_drop_oldest(self):
        st = ExemplarStore(per_tenant=2, min_window=4)
        for i in range(5):
            st.offer(FakeReq(dur_us=float(i), throttled=True))
        kept = st.exemplars("t")
        assert len(kept) == 2
        assert [e["dur_us"] for e in kept] == [3.0, 4.0]

    def test_clear(self):
        st = ExemplarStore()
        st.offer(FakeReq(throttled=True))
        st.clear()
        assert st.stats()["exemplars_offered"] == 0
        assert st.exemplars() == []


# ---------------------------------------------------------------- SLO engine
class TestSloEngine:
    def test_burn_rates_fast_and_slow_windows(self):
        t = [1000.0]
        eng = SloEngine(fast_s=60, slow_s=600, bucket_s=10,
                        clock=lambda: t[0],
                        default_target=SloTarget(gather_p99_us=1000.0,
                                                 objective_pct=90.0))
        for _ in range(8):
            eng.observe("a", 100.0)
        for _ in range(2):
            eng.observe("a", 50_000.0)  # bad
        fast, slow = eng.burn_rates("a")
        # 2 bad / 10 total = 0.2 bad frac over a 0.1 budget -> burn 2.0
        assert fast == pytest.approx(2.0)
        assert slow == pytest.approx(2.0)
        assert eng.burning("a")
        # advance past the fast window: the spike ages out of it but not
        # the slow one -> not burning any more (multi-window rule)
        t[0] += 120
        fast2, slow2 = eng.burn_rates("a")
        assert fast2 == 0.0 and slow2 == pytest.approx(2.0)
        assert not eng.burning("a")

    def test_queue_wait_counts_as_bad(self):
        eng = SloEngine(default_target=SloTarget(queue_wait_p99_us=100.0))
        eng.observe("a", 10.0, queue_wait_us=5_000.0)
        assert eng.burn_rates("a")[0] > 1.0

    def test_per_tenant_targets_and_report_gauges(self):
        from strom.utils.stats import global_stats

        eng = SloEngine()
        eng.set_target("tight", gather_p99_us=10.0, objective_pct=50.0)
        eng.observe("tight", 100.0)   # bad under the tight target
        eng.observe("loose", 100.0)   # good under the default
        rep = eng.report()
        assert rep["tenants"]["tight"]["slo_burning"]
        assert not rep["tenants"]["loose"]["slo_burning"]
        snap = global_stats.scoped(tenant="tight").snapshot()
        for g in SLO_FIELDS:
            assert g in snap, f"missing labeled gauge {g}"
        assert snap["slo_burning"] == 1

    def test_set_target_rejects_typos(self):
        with pytest.raises(TypeError):
            SloEngine().set_target("a", gather_p99_uss=5)

    def test_step_requests_do_not_feed_slo(self):
        eng = SloEngine(default_target=SloTarget(gather_p99_us=1.0))
        eng.observe_request(FakeReq(kind="step", dur_us=1e9))
        assert eng.burn_rates("t") == (0.0, 0.0)

    def test_ok_and_stats(self):
        eng = SloEngine(default_target=SloTarget(gather_p99_us=10.0,
                                                 objective_pct=50.0))
        assert eng.ok()
        eng.observe("a", 100.0)
        assert not eng.ok()
        s = eng.stats()
        assert s["slo_tenants"] == 1
        assert s["slo_tenants_burning"] == 1
        assert s["slo_worst_burn_fast"] > 1.0


# ------------------------------------------------------------------- history
class TestStatsHistory:
    def test_sample_rate_and_bounds(self):
        from strom.utils.stats import global_stats

        t = [100.0]
        h = StatsHistory(interval_s=1.0, capacity=5, clock=lambda: t[0],
                         start=False)
        c = global_stats.counter("history_test_bytes")
        for i in range(8):
            c.add(1000)
            h.sample()
            t[0] += 1.0
        samples = h.samples()
        assert len(samples) == 5  # bounded, drop-oldest
        assert h.rate("history_test_bytes") == pytest.approx(1000.0)
        assert h.rate("no_such_key") is None
        h.close()

    def test_scoped_series_and_key_filter(self):
        from strom.utils.stats import global_stats

        t = [0.0]
        h = StatsHistory(clock=lambda: t[0], start=False)
        scope = global_stats.scoped(tenant="ht0")
        scope.add("history_scoped_ops", 5)
        h.sample()
        t[0] += 2.0
        scope.add("history_scoped_ops", 5)
        h.sample()
        assert h.rate("history_scoped_ops",
                      scope='tenant="ht0"') == pytest.approx(2.5)
        keyed = h.samples(keys=["history_scoped_ops"])
        assert all(set(s) <= {"ts_s", "history_scoped_ops"} for s in keyed)
        h.close()


# ------------------------------------------------- server routes (new + conc)
class TestServerRoutes:
    def _get(self, port, route):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{route}", timeout=10) as r:
            return r.status, r.read()

    def test_trace_filters_and_stats_sections(self, tmp_path):
        from strom.delivery.core import StromContext

        p = str(tmp_path / "f.bin")
        with open(p, "wb") as f:
            f.write(os.urandom(64 * 1024))
        ctx = StromContext(StromConfig(engine="python", slab_pool_bytes=0,
                                       history_interval_s=0.1),
                           metrics_port=0)
        try:
            ctx.pread(p, 0, 4096)
            port = ctx.metrics_server.port
            _, body = self._get(port, "/trace?cat=read")
            doc = json.loads(body)
            cats = {e["cat"] for e in doc["traceEvents"]}
            assert cats <= {"read"} and cats
            _, body = self._get(port, "/trace?since_us=1e15")
            assert json.loads(body)["traceEvents"] == []
            _, body = self._get(port, "/stats?sections=slo")
            sections = json.loads(body)["sections"]
            assert "slo" in sections and "steps" not in sections
            _, body = self._get(port, "/slo")
            assert "tenants" in json.loads(body)
            time.sleep(0.3)
            _, body = self._get(port, "/history?keys=ssd2tpu_bytes")
            hist = json.loads(body)
            assert hist["samples"]
            assert all(set(s) <= {"ts_s", "ssd2tpu_bytes"}
                       for s in hist["samples"])
        finally:
            ctx.close()

    def test_post_tenants_concurrent_register_drain_never_500s(self):
        """ISSUE 8 satellite: parallel /tenants register/drain against a
        live scheduler must never 500 nor leak a partially-registered
        tenant (every registered row carries the full field set)."""
        from strom.delivery.core import StromContext

        ctx = StromContext(StromConfig(engine="python", slab_pool_bytes=0,
                                       hot_cache_bytes=8 << 20),
                           metrics_port=0)
        port = ctx.metrics_server.port
        bad: list = []

        def post(body: dict) -> int:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/tenants",
                data=json.dumps(body).encode(), method="POST")
            with urllib.request.urlopen(req, timeout=15) as r:
                return r.status

        def hammer(i: int) -> None:
            try:
                for k in range(6):
                    name = f"ct{(i + k) % 4}"
                    post({"op": "register", "name": name,
                          "priority": "training", "weight": 2,
                          "hot_cache_bytes": 1 << 20})
                    post({"op": "drain", "name": name, "timeout_s": 1})
                    self._get(port, "/tenants")
            except urllib.error.HTTPError as e:  # pragma: no cover
                bad.append(e.code)
            except Exception as e:  # pragma: no cover
                bad.append(repr(e))

        import urllib.error

        threads = [threading.Thread(target=hammer, args=(i,), daemon=True)
                   for i in range(6)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not bad, bad
            _, body = self._get(port, "/tenants")
            rows = json.loads(body)["tenants"]
            need = {"priority", "weight", "queued_ops", "byte_budget",
                    "hot_cache_bytes"}
            for name, row in rows.items():
                assert need <= set(row), f"partial tenant row {name}: {row}"
            # every hammered tenant registered exactly once, fully
            assert {f"ct{i}" for i in range(4)} <= set(rows)
        finally:
            ctx.close()


# ------------------------------------------------------- trace_report rollup
class TestTraceReportRequests:
    def test_critical_path_and_tenant_table(self, tmp_path):
        tr = _load_tool("trace_report")
        events = [
            # req 1: umbrella + queue -> read -> decode chain (one lane)
            {"ph": "X", "ts_us": 0.0, "dur_us": 100.0, "tid": 1,
             "cat": "batch", "name": "umbrella", "args": {"req": 1}},
            {"ph": "X", "ts_us": 0.0, "dur_us": 10.0, "tid": 1,
             "cat": "sched", "name": "sched.queue", "args": {"req": 1}},
            {"ph": "X", "ts_us": 10.0, "dur_us": 50.0, "tid": 1,
             "cat": "read", "name": "engine.slice", "args": {"req": 1}},
            {"ph": "X", "ts_us": 60.0, "dur_us": 40.0, "tid": 2,
             "cat": "decode", "name": "decode.worker", "args": {"req": 1}},
            {"ph": "i", "ts_us": 100.0, "tid": 1, "cat": "req",
             "name": "req.done",
             "args": {"req": 1, "tenant": "t0", "kind": "batch",
                      "dur_us": 100.0, "throttled": True}},
            {"ph": "i", "ts_us": 5.0, "tid": 1, "cat": "req",
             "name": "req.done",
             "args": {"req": 2, "tenant": "t1", "kind": "gather",
                      "dur_us": 5.0}},
        ]
        rows = tr.request_rollup(events)
        assert rows[0]["req"] == 1 and rows[0]["throttled"]
        # the umbrella span is excluded; the chain is the causal sequence
        assert rows[0]["path"].split("→")[0].startswith("sched.queue")
        assert "engine.slice" in rows[0]["path"]
        assert "decode.worker" in rows[0]["path"]
        assert "umbrella" not in rows[0]["path"]
        tenants = tr.tenant_table(events)
        assert [t[0] for t in tenants] == ["t0", "t1"]
        assert tenants[0][4] == 1  # throttled count

    def test_report_renders_request_sections(self, tmp_path, capsys):
        tr = _load_tool("trace_report")
        from strom.obs import chrome_trace

        ring = EventRing(capacity=64)
        req = obs_request.Request("gather", "tr0")
        with obs_request.attach(req):
            with req.span("strom.read_segments", cat="read"):
                time.sleep(0.001)
        req.finish()
        p = str(tmp_path / "t.json")
        chrome_trace.dump(p, ring=global_ring)
        assert tr.main([p]) == 0
        out = capsys.readouterr().out
        assert "slowest requests" in out
        assert "tenant" in out


# ----------------------------------------------------------------- strom_top
class TestStromTop:
    def test_rows_and_render_pure(self):
        top = _load_tool("strom_top")
        cur = {
            "t": 10.0,
            "global": {"pipeline_steps": 3, "ssd2tpu_bytes": 1 << 20},
            "sections": {"sched": {"sched_active_grants": 1,
                                   "sched_queued_ops": 2,
                                   "slab_pool_admission_waits": 0}},
            "scopes": {"t0": {"sched_queue_wait_p99_us": 2048.0,
                              "sched_granted_bytes": 3_000_000,
                              "cache_hit_bytes": 75, "cache_miss_bytes": 25}},
            "tenants": {"t0": {"priority": "training", "queued_ops": 2,
                               "active_grants": 1, "slo_burning": True}},
            "admission": {}, "slo": {"t0": {"slo_burn_fast": 3.0,
                                            "slo_burn_slow": 2.0,
                                            "slo_burning": True}},
        }
        prev = {"t": 9.0, "scopes": {"t0": {"sched_granted_bytes":
                                            1_000_000}},
                "tenants": {}, "slo": {}, "global": {}, "sections": {},
                "admission": {}}
        rows = top.rows(cur, prev)
        assert rows[0]["tenant"] == "t0"
        assert rows[0]["granted_mb_s"] == pytest.approx(2.0)
        assert rows[0]["hit_pct"] == pytest.approx(75.0)
        assert rows[0]["slo"] == "BURNING"
        text = top.render(cur, prev)
        assert "t0" in text and "BURNING" in text

    def test_scope_tenant_extraction_prefers_pure_scope(self):
        top = _load_tool("strom_top")
        scopes = {
            'pipeline="resnet",tenant="t0"': {"a": 1},
            'tenant="t0"': {"a": 2},
        }
        assert top._scope_tenants(scopes)["t0"]["a"] == 2


# -------------------------------------------------------------- acceptance
class TestAcceptance:
    @pytest.fixture()
    def wds(self, tmp_path):
        cv2 = pytest.importorskip("cv2")
        from tests.test_formats import make_wds_shard

        rng = np.random.default_rng(9)
        samples = []
        for i in range(8):
            img = rng.integers(0, 256, (48, 48, 3), dtype=np.uint8)
            ok, buf = cv2.imencode(".jpg", img)
            assert ok
            samples.append((f"s{i:04d}", {"jpg": buf.tobytes(),
                                          "cls": str(i % 3).encode()}))
        p = str(tmp_path / "acc.tar")
        make_wds_shard(p, samples)
        return [p]

    def test_two_tenant_slow_gather_end_to_end(self, wds, tmp_path,
                                               capsys):
        """The ISSUE 8 acceptance criterion, in one scenario."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from strom.delivery.core import StromContext
        from strom.obs import chrome_trace
        from strom.parallel.mesh import make_mesh
        from strom.pipelines import make_wds_vision_pipeline

        global_ring.clear()
        global_store.clear()
        datafile = str(tmp_path / "data.bin")
        with open(datafile, "wb") as f:
            f.write(os.urandom(1 << 20))

        ctx = StromContext(StromConfig(engine="python", slab_pool_bytes=0,
                                       history_interval_s=0.1),
                           metrics_port=0)
        try:
            # two tenants: "fast" unbudgeted interactive, "slow" strangled
            # by a tiny byte budget so its gathers queue on refills
            ctx.register_tenant("fast", priority="interactive")
            ctx.register_tenant("slow", byte_rate=1e6, byte_burst=1024)
            ctx.slo.set_target("slow", gather_p99_us=20_000,
                               queue_wait_p99_us=10_000)

            # seed the fast tenant's rolling window with quick gathers
            for _ in range(20):
                ctx.pread(datafile, 0, 4096, tenant="fast")
            # the deliberately slow gathers: the first rides the burst,
            # the rest wait out the 1MB/s refill (throttled + slow)
            for _ in range(3):
                ctx.pread(datafile, 0, 256 * 1024, tenant="slow")

            # one traced vision batch so the decode/put lane exists
            mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
            sharding = NamedSharding(mesh, P("dp", None, None, None))
            pipe = make_wds_vision_pipeline(
                ctx, wds, batch=4, image_size=32, sharding=sharding,
                decode_workers=2,
                scope={"pipeline": "resnet", "tenant": "fast"})
            try:
                next(pipe)[0].block_until_ready()
            finally:
                pipe.close()

            # ---- (a) Perfetto-loadable flow-connected trace ------------
            trace_path = str(tmp_path / "acc_trace.json")
            chrome_trace.dump(trace_path)
            events = chrome_trace.load_events(trace_path)
            spans = {}
            for e in events:
                rid = (e.get("args") or {}).get("req")
                if rid is not None and e["ph"] == "X":
                    spans.setdefault(rid, set()).add(e["name"])
            # a slow-tenant gather: queue -> grant -> engine slice, one id
            slow_req = next(
                rid for rid, names in spans.items()
                if "engine.slice" in names and "sched.queue" in names)
            assert {"sched.queue", "sched.grant", "engine.slice",
                    "strom.read_segments"} <= spans[slow_req]
            # the batch request: decode + put joined the same lane
            batch_req = next(
                rid for rid, names in spans.items()
                if "decode.worker" in names)
            assert "strom.device_put" in spans[batch_req]
            assert {"sched.queue", "sched.grant"} <= spans[batch_req]
            # flow events connect each lane (s first, then t's)
            flows = [e for e in events if e["ph"] in ("s", "t")]
            for rid in (slow_req, batch_req):
                chain = [e for e in flows if e.get("id") == rid]
                assert chain and chain[0]["ph"] == "s"
                assert all(e["ph"] == "t" for e in chain[1:])

            # ---- (b) exemplar store: slow retained, fast not -----------
            kept_slow = global_store.exemplars("slow")
            assert kept_slow, "throttled slow gathers must be retained"
            assert any(e["throttled"] for e in kept_slow)
            assert all(
                {"sched.queue", "strom.read_segments"}
                <= {s["name"] for s in e["spans"]} for e in kept_slow)
            # the fast tenant's plain preads were offered and discarded
            assert global_store.exemplars("fast") == []
            st = global_store.stats()
            assert st["exemplars_discarded"] >= 20

            # ---- (c) /slo burn + /tenants flag + strom_top -------------
            port = ctx.metrics_server.port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/slo", timeout=10) as r:
                slo = json.loads(r.read())
            assert slo["tenants"]["slow"]["slo_burning"]
            assert slo["tenants"]["slow"]["slo_burn_fast"] > 1.0
            assert not slo["tenants"]["fast"]["slo_burning"]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/tenants", timeout=10) as r:
                tenants = json.loads(r.read())
            assert tenants["tenants"]["slow"]["slo_burning"] is True
            assert tenants["tenants"]["fast"]["slo_burning"] is False

            top = _load_tool("strom_top")
            assert top.main(["--port", str(port), "--once"]) == 0
            out = capsys.readouterr().out
            assert "slow" in out and "fast" in out
            assert "BURNING" in out
        finally:
            ctx.close()


# ------------------------------------------------- review-hardening checks
class TestPerContextOwnership:
    """Requests carry their minting context's owner token; the process-
    global observer list must not let one context's SLO engine ingest a
    concurrent context's requests."""

    def test_owned_requests_feed_only_their_context(self):
        from strom.delivery.core import StromContext

        ctx_a = StromContext(StromConfig(engine="python", slab_pool_bytes=0))
        ctx_b = StromContext(StromConfig(engine="python", slab_pool_bytes=0))
        try:
            with obs_request.active("gather", "own_a",
                                    owner=ctx_a._req_owner):
                pass
            assert "own_a" in ctx_a.slo.tenants()
            assert "own_a" not in ctx_b.slo.tenants()
            # unowned requests (bare mint sites) are seen by every context
            with obs_request.active("gather", "own_none"):
                pass
            assert "own_none" in ctx_a.slo.tenants()
            assert "own_none" in ctx_b.slo.tenants()
        finally:
            ctx_a.close()
            ctx_b.close()

    def test_gathers_and_pipeline_steps_are_owner_stamped(self, tmp_path):
        from strom.delivery.core import StromContext

        p = str(tmp_path / "own.bin")
        with open(p, "wb") as f:
            f.write(os.urandom(8192))
        ctx = StromContext(StromConfig(engine="python", slab_pool_bytes=0))
        seen: list = []
        obs_request.add_observer(seen.append)
        try:
            ctx.pread(p, 0, 4096, tenant="ownt")
            gathers = [r for r in seen if r.kind == "gather"]
            assert gathers and all(r.owner is ctx._req_owner
                                   for r in gathers)
        finally:
            obs_request.remove_observer(seen.append)
            ctx.close()

    def test_grant_span_parent_captured_at_entry(self):
        """A streamed gather releases its grant on the pump thread; the
        sched.grant span must still parent-link to the span that was open
        on the SUBMITTING thread at entry, not the exit thread's stack."""
        from strom.delivery.core import StromContext

        ctx = StromContext(StromConfig(engine="python", slab_pool_bytes=0))
        try:
            req = obs_request.Request("gather", "gp0")
            with obs_request.attach(req):
                with req.span("outer.gather", cat="read"):
                    cm = ctx.scheduler.grant("gp0", 4096)
                    cm.__enter__()
            t = threading.Thread(target=cm.__exit__, args=(None,) * 3)
            t.start()
            t.join(timeout=30)
            by_name = {s[0]: s for s in req.spans}
            assert "sched.grant" in by_name
            assert by_name["sched.grant"][5] == "outer.gather"
        finally:
            ctx.close()


class TestMetricsSloRefresh:
    def test_metrics_scrape_alone_refreshes_slo_gauges(self):
        """The documented contract is labeled slo_* gauges on /metrics; a
        Prometheus-only deployment never hits /slo, so the scrape itself
        must refresh the burn-rate gauges."""
        from strom.delivery.core import StromContext

        ctx = StromContext(StromConfig(engine="python", slab_pool_bytes=0),
                           metrics_port=0)
        try:
            ctx.slo.set_target("m0", gather_p99_us=10.0)
            for _ in range(5):
                ctx.slo.observe("m0", 1000.0)
            port = ctx.metrics_server.port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                body = r.read().decode()
            burning = [ln for ln in body.splitlines()
                       if "slo_burning" in ln and 'tenant="m0"' in ln]
            assert burning, "labeled slo_burning gauge missing from /metrics"
            assert all(ln.rsplit(" ", 1)[1] == "1" for ln in burning)
        finally:
            ctx.close()


class TestTenantTableKinds:
    def test_tenant_table_excludes_step_requests(self):
        """Per-tenant percentiles must match req_lat's data-path-only
        policy: a step marker's (compute-dominated) wall never skews them."""
        tr = _load_tool("trace_report")
        events = [
            {"ph": "i", "ts_us": 1.0, "tid": 1, "cat": "req",
             "name": "req.done",
             "args": {"req": 1, "tenant": "t0", "kind": "gather",
                      "dur_us": 100.0}},
            {"ph": "i", "ts_us": 2.0, "tid": 1, "cat": "req",
             "name": "req.done",
             "args": {"req": 2, "tenant": "t0", "kind": "step",
                      "dur_us": 9e9}},
        ]
        rows = tr.tenant_table(events)
        assert len(rows) == 1
        tenant, n, p50_ms, p99_ms, throttled, errors = rows[0]
        assert tenant == "t0" and n == 1
        assert p99_ms == pytest.approx(0.1)
