"""strom/obs — event ring, Chrome-trace export, live endpoint, stall
attribution (ISSUE 3 tentpole). The ring is the causal timeline the counters
cannot provide; these tests pin its bounded-drop semantics, the export
format Perfetto actually loads, the HTTP routes, and the bucket arithmetic
the next perf PR will be chosen with."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from strom.obs import stall
from strom.obs.chrome_trace import (dump, load_events, to_trace_events,
                                    trace_document)
from strom.obs.events import EventRing
from strom.obs.server import MetricsServer


class TestEventRing:
    def test_span_and_instant_shapes(self):
        r = EventRing(capacity=16)
        with r.span("work", cat="read", args={"bytes": 7}):
            pass
        r.instant("tick", cat="meta")
        evs = r.snapshot()
        assert [e["name"] for e in evs] == ["work", "tick"]
        span, inst = evs
        assert span["ph"] == "X" and span["dur_us"] >= 0
        assert span["cat"] == "read" and span["args"] == {"bytes": 7}
        assert inst["ph"] == "i" and "dur_us" not in inst
        assert span["tid"] == threading.get_ident()

    def test_bounded_drop_oldest(self):
        r = EventRing(capacity=4)
        for i in range(10):
            r.instant(f"e{i}")
        evs = [e for e in r.snapshot() if e["name"] != "events_dropped"]
        # only the newest `capacity` retained, oldest first
        assert [e["name"] for e in evs] == ["e6", "e7", "e8", "e9"]
        assert r.events_dropped == 6
        # the truncation is visible in the snapshot itself, never silent
        meta = [e for e in r.snapshot() if e["name"] == "events_dropped"]
        assert meta and meta[0]["args"]["count"] == 6

    def test_disabled_ring_records_nothing(self):
        r = EventRing(capacity=8, enabled=False)
        with r.span("x"):
            r.instant("y")
        assert r.snapshot() == [] and len(r) == 0

    def test_span_recorded_on_exception(self):
        r = EventRing(capacity=8)
        with pytest.raises(ValueError):
            with r.span("boom", cat="read"):
                raise ValueError()
        assert [e["name"] for e in r.snapshot()] == ["boom"]

    def test_snapshot_sorted_by_start_despite_nesting(self):
        r = EventRing(capacity=8)
        with r.span("outer"):  # exits LAST, starts FIRST
            with r.span("inner"):
                pass
        names = [e["name"] for e in r.snapshot()]
        assert names == ["outer", "inner"]

    def test_clear(self):
        r = EventRing(capacity=4)
        for i in range(9):
            r.instant("e")
        r.clear()
        assert r.snapshot() == [] and r.events_dropped == 0

    def test_concurrent_writers_never_corrupt(self):
        r = EventRing(capacity=64)

        def spam():
            for _ in range(500):
                r.instant("t")

        ts = [threading.Thread(target=spam) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        evs = r.snapshot()
        assert len(evs) == 64 + 1  # 64 retained + the events_dropped marker
        assert r.events_dropped == 4 * 500 - 64


class TestChromeTrace:
    def test_export_and_roundtrip(self, tmp_path):
        r = EventRing(capacity=16)
        with r.span("engine.read", cat="read", args={"ops": 3}):
            pass
        r.instant("prefetch.depth", cat="prefetch", args={"depth": 4})
        p = str(tmp_path / "trace.json")
        assert dump(p, ring=r) == p
        doc = json.loads(open(p).read())
        assert "traceEvents" in doc
        tes = doc["traceEvents"]
        assert {te["ph"] for te in tes} == {"X", "i"}
        x = next(te for te in tes if te["ph"] == "X")
        assert x["name"] == "engine.read" and x["cat"] == "read"
        assert "dur" in x and "ts" in x and "pid" in x and "tid" in x
        # loader inverts the export (tools/trace_report.py rides this)
        evs = load_events(p)
        assert [e["name"] for e in evs] == ["engine.read", "prefetch.depth"]
        assert evs[0]["cat"] == "read" and evs[0]["args"] == {"ops": 3}

    def test_instant_scope_and_meta(self):
        doc = trace_document(
            [{"ts_us": 1.0, "tid": 5, "cat": "", "name": "i", "ph": "i"}],
            meta={"bench": "resnet"})
        te = doc["traceEvents"][0]
        assert te["s"] == "t" and te["cat"] == "strom"
        assert doc["otherData"] == {"bench": "resnet"}

    def test_to_trace_events_pure(self):
        tes = to_trace_events(
            [{"ts_us": 10.0, "dur_us": 5.0, "tid": 1, "cat": "put",
              "name": "p", "ph": "X"}], pid=42)
        assert tes == [{"name": "p", "ph": "X", "ts": 10.0, "pid": 42,
                        "tid": 1, "cat": "put", "dur": 5.0}]


def _span(ts, dur, cat, name="s", tid=1):
    return {"ts_us": float(ts), "dur_us": float(dur), "tid": tid,
            "cat": cat, "name": name, "ph": "X"}


class TestStallAttribution:
    def test_buckets_from_synthetic_timeline(self):
        # step [0, 100]: waits 30us in next() at [0, 30]; during the wait
        # decode ran [0, 20], put [20, 30], engine read [5, 15]; decode also
        # ran [50, 90] OVERLAPPING COMPUTE — free, must not be billed
        events = [
            _span(0, 100, "step", "train.step"),
            _span(0, 30, "ingest_wait", "pipeline.next"),
            _span(0, 20, "decode", "decode.worker", tid=2),
            _span(50, 40, "decode", "decode.worker", tid=2),
            _span(20, 10, "put", "strom.device_put", tid=3),
            _span(5, 10, "read", "engine.python.read_vectored", tid=4),
        ]
        (s,) = stall.step_buckets(events)
        assert s.wall_us == 100 and s.ingest_wait_us == 30
        assert s.decode_us == 20 and s.put_us == 10 and s.read_us == 10
        assert s.compute_us == 70
        summary = stall.steps_summary(events)
        assert summary["steps_observed"] == 1
        assert summary["goodput_pct"] == 70.0
        assert summary["buckets"]["ingest_wait"]["p50_us"] == 30
        assert summary["buckets"]["compute"]["total_us"] == 70

    def test_overlapping_waits_union_not_double_billed(self):
        # pipeline.next and prefetch.stall_wait overlap (nested): the wait
        # bucket is their UNION, not the sum
        events = [
            _span(0, 100, "step", "train.step"),
            _span(10, 40, "ingest_wait", "pipeline.next"),
            _span(15, 30, "ingest_wait", "prefetch.stall_wait"),
        ]
        (s,) = stall.step_buckets(events)
        assert s.ingest_wait_us == 40 and s.compute_us == 60

    def test_steps_derived_from_waits_when_no_step_spans(self):
        # flat-out loader shape: no train.step spans — windows derive from
        # consecutive next() starts, and the FINAL next() still gets a
        # window (closed at the last event edge: N nexts -> N windows)
        events = [
            _span(0, 10, "ingest_wait", "pipeline.next"),
            _span(50, 20, "ingest_wait", "pipeline.next"),
            _span(100, 5, "ingest_wait", "pipeline.next"),
        ]
        steps = stall.step_buckets(events)
        assert [s.wall_us for s in steps] == [50, 50, 5]
        assert [s.ingest_wait_us for s in steps] == [10, 20, 5]

    def test_nested_stall_wait_does_not_split_windows(self):
        # a stalled next() emits BOTH a pipeline.next span and a nested
        # prefetch.stall_wait span (same cat): window derivation must not
        # count the nested span as an extra step boundary
        events = [
            _span(0, 30, "ingest_wait", "pipeline.next"),
            _span(5, 20, "ingest_wait", "prefetch.stall_wait"),
            _span(50, 10, "ingest_wait", "pipeline.next"),
        ]
        steps = stall.step_buckets(events)
        assert len(steps) == 2
        assert [s.wall_us for s in steps] == [50, 10]

    def test_single_next_still_yields_a_window(self):
        events = [
            _span(0, 10, "ingest_wait", "pipeline.next"),
            _span(2, 30, "read", "strom.read_segments", tid=2),
        ]
        (s,) = stall.step_buckets(events)
        assert s.wall_us == 32 and s.ingest_wait_us == 10

    def test_window_bounds_filter(self):
        events = [
            _span(0, 10, "step", "train.step"),
            _span(100, 10, "step", "train.step"),
        ]
        assert len(stall.step_buckets(events, lo_us=50)) == 1
        assert len(stall.step_buckets(events, hi_us=50)) == 1
        assert stall.steps_summary(events, lo_us=50)["steps_observed"] == 1

    def test_empty_events(self):
        summary = stall.steps_summary([])
        assert summary["steps_observed"] == 0
        assert summary["goodput_pct"] == 0.0
        flat = stall.flatten_summary(summary)
        assert flat["goodput_pct"] == 0.0
        assert set(stall.STALL_FIELDS) <= set(flat)

    def test_flatten_matches_stall_fields(self):
        # the bench JSON column contract: flatten_summary emits EXACTLY the
        # single-sourced STALL_FIELDS key set
        flat = stall.flatten_summary(stall.steps_summary(
            [_span(0, 10, "step", "train.step")]))
        assert set(flat) == set(stall.STALL_FIELDS)


class TestMetricsServer:
    def _get(self, port, route):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{route}", timeout=10) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:  # 4xx/5xx still carry a body
            return e.code, e.read().decode()

    def test_routes(self):
        from strom.utils.stats import StatsRegistry

        reg = StatsRegistry("obs_test")
        reg.add("scrapes_total", 3)
        reg.observe_us("lat", 100.0)
        ring = EventRing(capacity=8)
        with ring.span("engine.read", cat="read"):
            pass
        srv = MetricsServer(lambda: {"sec": reg.snapshot()}, port=0,
                            ring=ring)
        try:
            st, metrics = self._get(srv.port, "/metrics")
            assert st == 200
            assert "strom_sec_scrapes_total 3" in metrics
            # live histogram, cumulative, with TYPE line (acceptance: at
            # least one live histogram in a /metrics scrape)
            assert "# TYPE strom_sec_lat_us histogram" in metrics
            assert 'strom_sec_lat_us_bucket{le="+Inf"} 1' in metrics

            st, body = self._get(srv.port, "/stats")
            doc = json.loads(body)
            assert st == 200
            assert doc["sections"]["sec"]["scrapes_total"] == 3
            assert "global" in doc and doc["events_dropped"] == 0

            st, body = self._get(srv.port, "/trace")
            assert st == 200
            tes = json.loads(body)["traceEvents"]
            assert any(te["name"] == "engine.read" for te in tes)

            st, _ = self._get(srv.port, "/nope")
            assert st == 404
        finally:
            srv.close()

    def test_get_raises_404_after_close_or_refuses(self):
        srv = MetricsServer(lambda: {}, port=0)
        port = srv.port
        srv.close()
        with pytest.raises(Exception):
            self._get(port, "/metrics")

    def test_stats_fn_error_returns_500_not_crash(self):
        def bad():
            raise RuntimeError("boom")

        srv = MetricsServer(bad, port=0)
        try:
            st, _ = self._get(srv.port, "/stats")
            assert st == 500
            # server survives the failed scrape
            st, _ = self._get(srv.port, "/trace")
            assert st == 200
        finally:
            srv.close()

    def test_metrics_without_stats_fn_serves_global_registry(self):
        from strom.utils.stats import global_stats

        global_stats.add("obs_server_test_hits")
        srv = MetricsServer(port=0)
        try:
            st, body = self._get(srv.port, "/metrics")
            assert st == 200 and "strom_obs_server_test_hits" in body
        finally:
            srv.close()

    def test_sections_filter_skips_unwanted(self):
        """?sections= restricts the sweep AND the server only asks
        stats_fn for the wanted sections (ISSUE 6 satellite: a
        counters-only scrape never recomputes stall attribution)."""
        calls = []

        def stats_fn(sections=None):
            calls.append(tuple(sections) if sections is not None else None)
            secs = {"cheap": {"a": 1}, "costly": {"b": 2}}
            if sections is None:
                return secs
            return {k: v for k, v in secs.items() if k in sections}

        srv = MetricsServer(stats_fn, port=0, section_ttl_s=0.0)
        try:
            # first scrape learns the section names (full compute, once)
            st, body = self._get(srv.port, "/metrics?sections=cheap")
            assert st == 200
            assert "strom_cheap_a 1" in body
            st, body = self._get(srv.port, "/metrics?sections=cheap")
            assert "strom_cheap_a 1" in body and "strom_costly_b" not in body
            # after warmup, refreshes name only the wanted section
            assert calls[-1] == ("cheap",)
        finally:
            srv.close()

    def test_section_ttl_caches_renders(self):
        calls = []

        def stats_fn(sections=None):
            calls.append(1)
            return {"sec": {"n": len(calls)}}

        srv = MetricsServer(stats_fn, port=0, section_ttl_s=60.0)
        try:
            _, body1 = self._get(srv.port, "/metrics")
            n_after_first = len(calls)
            _, body2 = self._get(srv.port, "/metrics")
            # within the TTL the rendered text is reused: no new compute
            assert len(calls) == n_after_first
            assert "strom_sec_n 1" in body1 and "strom_sec_n 1" in body2
        finally:
            srv.close()

    def test_scoped_series_render_with_help_and_type(self):
        """Labeled twins of a scoped write appear under ONE # HELP/# TYPE
        family header on /metrics (ISSUE 6 satellite)."""
        from strom.utils.stats import global_stats

        global_stats.scoped(pipeline="obs_t").add("obs_scoped_probe", 4)
        srv = MetricsServer(port=0)
        try:
            _, body = self._get(srv.port, "/metrics")
            assert "# TYPE strom_obs_scoped_probe counter" in body
            assert body.count("# TYPE strom_obs_scoped_probe ") == 1
            assert 'strom_obs_scoped_probe{pipeline="obs_t"} 4' in body
        finally:
            srv.close()


class TestWiring:
    """The instrumentation sites actually emit: one pread lights up the
    read spans; a context exposes the steps section; trace_span feeds the
    ring even without a jax profiler session."""

    def test_trace_span_dual_emit(self):
        from strom.obs.events import ring as groll
        from strom.utils.tracing import trace_span

        before = len(groll)
        with trace_span("obs.test.span", cat="put"):
            pass
        evs = [e for e in groll.snapshot()
               if e["name"] == "obs.test.span"]
        assert evs and evs[-1]["cat"] == "put"
        assert len(groll) > before

    def test_trace_span_enabled_false_still_feeds_ring(self):
        """enabled= gates the jax annotation only: turning annotations off
        must not zero the put bucket while directly-instrumented sites
        (read/decode/step) keep recording."""
        from strom.obs.events import ring as groll
        from strom.utils.tracing import trace_span

        with trace_span("obs.test.annot_off", cat="put", enabled=False):
            pass
        assert any(e["name"] == "obs.test.annot_off"
                   for e in groll.snapshot())

    def test_trace_span_respects_ring_switch(self):
        from strom.obs.events import ring as groll
        from strom.utils.tracing import trace_span

        groll.enabled = False
        try:
            before = len(groll)
            with trace_span("obs.test.ring_off", cat="put"):
                pass
            assert len(groll) == before
        finally:
            groll.enabled = True

    def test_pread_emits_read_spans_and_steps_section(self, tmp_path, rng):
        from strom.config import StromConfig
        from strom.delivery.core import StromContext
        from strom.obs.events import ring as groll

        data = rng.integers(0, 256, 64 * 1024, dtype=np.uint8)
        p = tmp_path / "obs.bin"
        data.tofile(p)
        ctx = StromContext(StromConfig(engine="python"))
        try:
            got = ctx.pread(str(p), 0, 4096)
            np.testing.assert_array_equal(got, data[:4096])
            names = {e["name"] for e in groll.snapshot()}
            assert "strom.read_segments" in names
            assert "engine.python.read_vectored" in names
            st = ctx.stats()
            assert "steps" in st
            assert set(st["steps"]) >= set(
                ["goodput_pct", "steps_observed", "events_dropped"])
        finally:
            ctx.close()

    def test_context_metrics_port_serves_live_stats(self, tmp_path, rng):
        """StromContext(metrics_port=0) binds an ephemeral port and serves
        the context's own sections + the global registry mid-run."""
        from strom.config import StromConfig
        from strom.delivery.core import StromContext

        data = rng.integers(0, 256, 8192, dtype=np.uint8)
        p = tmp_path / "live.bin"
        data.tofile(p)
        ctx = StromContext(StromConfig(engine="python"), metrics_port=0)
        try:
            assert ctx.metrics_server is not None
            ctx.pread(str(p), 0, 4096)
            port = ctx.metrics_server.port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                text = r.read().decode()
            # context/engine sections present, counters typed as counters
            assert "strom_engine_bytes_read" in text
            assert "# TYPE strom_context_ssd2tpu_bytes counter" in text
            # live engine histogram (the acceptance criterion's shape)
            assert "strom_engine_read_latency_us_bucket" in text
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/stats", timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert doc["sections"]["engine"]["bytes_read"] >= 4096
        finally:
            ctx.close()
        assert ctx.metrics_server is not None  # handle survives for .port

    def test_decode_pool_emits_decode_spans(self):
        from strom.formats.jpeg import DecodePool
        from strom.obs.events import ring as groll

        def tf(item, rng_, out=None):
            out[...] = item
            return out

        pool = DecodePool(workers=2)
        try:
            out = np.zeros((4, 2, 2, 3), dtype=np.uint8)
            pool.map_into(tf, [1, 2, 3, 4], [None] * 4, out)
        finally:
            pool.close()
        decs = [e for e in groll.snapshot()
                if e["name"] == "decode.worker" and e["cat"] == "decode"]
        assert len(decs) >= 4

    def test_prefetcher_stall_events_and_global_gauge(self):
        import time as _time

        from strom.delivery.prefetch import Prefetcher
        from strom.obs.events import ring as groll
        from strom.utils.stats import global_stats

        def slow():
            _time.sleep(0.05)
            return 1

        pf = Prefetcher(iter([slow, slow]), depth=1)
        try:
            assert next(pf) == 1
            assert next(pf) == 1
        finally:
            pf.close()
        assert pf.data_stall_steps >= 1
        # satellite: the stall counter is mirrored into the GLOBAL registry
        # (appears in /metrics and bench JSON without bespoke plumbing)
        assert global_stats.gauge("prefetch_data_stall_steps").value \
            == pf.data_stall_steps
        assert global_stats.gauge("prefetch_depth").value >= 1
        evs = groll.snapshot()
        assert any(e["name"] == "prefetch.stall_wait"
                   and e["cat"] == "ingest_wait" for e in evs)
        assert any(e["name"] == "prefetch.state"
                   and e["args"]["state"] == "stall" for e in evs)
