"""Engine-native checkpointing (ISSUE 13 tentpole): save/restore round-trip
bit-exactness through the write path, crash-safe commit semantics, CRC
corruption detection, and the pickle baseline's own round-trip."""

import json
import os

import numpy as np
import pytest

from strom.config import StromConfig
from strom.delivery.core import StromContext
from strom.ckpt import (CkptCorruptError, CkptError, load_pickle,
                        restore_checkpoint, save_checkpoint, save_pickle)

jax = pytest.importorskip("jax")
jnp = jax.numpy


@pytest.fixture()
def ctx():
    c = StromContext(StromConfig(engine="python", queue_depth=8,
                                 num_buffers=16,
                                 slab_pool_bytes=64 * 1024 * 1024))
    yield c
    c.close()


def _state():
    return {
        "params": {"w": jnp.arange(1 << 16, dtype=jnp.float32)
                   .reshape(256, 256),
                   "b": jnp.ones((512,), dtype=jnp.bfloat16)},
        "opt": [jnp.full((123, 7), 3.5, dtype=jnp.float32),
                np.arange(11, dtype=np.int64)],
        "empty": np.zeros((0, 4), dtype=np.float32),
        "step": 42,
    }


def _assert_tree_equal(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestRoundTrip:
    @pytest.mark.parametrize("verify", [False, True])
    def test_bit_exact(self, ctx, tmp_path, verify):
        """Save via engine writes, restore via memcpy_ssd2tpu (verify=False)
        / host CRC-checked read (verify=True): bit-exact, dtypes (bfloat16
        included) and python-scalar leaves preserved."""
        state = _state()
        d = str(tmp_path / "ckpt")
        m = save_checkpoint(ctx, d, state)
        assert m["payload_bytes"] > 0
        back = restore_checkpoint(ctx, d, state, verify=verify)
        _assert_tree_equal(state, back)
        assert back["step"] == 42 and isinstance(back["step"], int)

    def test_resave_replaces_atomically(self, ctx, tmp_path):
        """A second save to the same directory replaces the checkpoint (new
        inode): restore sees the NEW state — no stale fd, no stale cache."""
        state = _state()
        d = str(tmp_path / "ckpt")
        save_checkpoint(ctx, d, state)
        state2 = dict(state)
        state2["step"] = 43
        state2["params"] = {"w": state["params"]["w"] + 1,
                            "b": state["params"]["b"]}
        save_checkpoint(ctx, d, state2)
        back = restore_checkpoint(ctx, d, state2)
        _assert_tree_equal(state2, back)
        assert back["step"] == 43

    def test_leaf_spans_are_aligned(self, ctx, tmp_path):
        d = str(tmp_path / "ckpt")
        m = save_checkpoint(ctx, d, _state())
        for leaf in m["leaves"]:
            assert leaf["offset"] % 4096 == 0


class TestFailureModes:
    def test_corrupt_data_detected(self, ctx, tmp_path):
        state = _state()
        d = str(tmp_path / "ckpt")
        m = save_checkpoint(ctx, d, state)
        # flip a byte INSIDE a real leaf span (the inter-span alignment
        # padding is uncovered by design — nothing reads it)
        leaf = next(lf for lf in m["leaves"] if lf["nbytes"] > 16)
        data = os.path.join(d, "data.bin")
        with open(data, "r+b") as f:
            f.seek(leaf["offset"] + 10)
            b0 = f.read(1)
            f.seek(leaf["offset"] + 10)
            f.write(bytes([b0[0] ^ 0x01]))
        ctx.invalidate_file(data)
        with pytest.raises(CkptCorruptError):
            restore_checkpoint(ctx, d, state, verify=True)

    def test_template_shape_mismatch(self, ctx, tmp_path):
        state = _state()
        d = str(tmp_path / "ckpt")
        save_checkpoint(ctx, d, state)
        bad = dict(state)
        bad["opt"] = [jnp.zeros((5, 5), dtype=jnp.float32),
                      state["opt"][1]]
        with pytest.raises(CkptError):
            restore_checkpoint(ctx, d, bad)

    def test_not_a_checkpoint(self, ctx, tmp_path):
        d = tmp_path / "nope"
        d.mkdir()
        with pytest.raises(CkptError):
            restore_checkpoint(ctx, str(d), _state())

    def test_failed_save_leaves_old_checkpoint_intact(self, ctx, tmp_path,
                                                      monkeypatch):
        """A save that dies mid-write (writer failure) cleans its tmp dir
        and leaves the previous committed checkpoint restorable — the
        tmp+rename crash-safety contract."""
        state = _state()
        d = str(tmp_path / "ckpt")
        save_checkpoint(ctx, d, state)

        real = ctx.write_chunks

        def dying(chunks, src, **kw):
            raise OSError("injected writer death")

        monkeypatch.setattr(ctx, "write_chunks", dying)
        with pytest.raises(Exception):
            save_checkpoint(ctx, d, dict(state, step=99))
        monkeypatch.setattr(ctx, "write_chunks", real)
        # tmp orphan cleaned; the OLD checkpoint restores bit-exact
        assert not any(n.startswith("ckpt.tmp")
                       for n in os.listdir(str(tmp_path)))
        back = restore_checkpoint(ctx, d, state, verify=True)
        _assert_tree_equal(state, back)
        assert back["step"] == 42

    def test_manifest_crcs_are_real(self, ctx, tmp_path):
        """The manifest CRCs (computed during staging, ISSUE 13) match an
        independent recomputation from the bytes on disk."""
        import zlib

        state = _state()
        d = str(tmp_path / "ckpt")
        m = save_checkpoint(ctx, d, state)
        with open(os.path.join(d, "manifest.json")) as f:
            assert json.load(f) == m
        with open(os.path.join(d, "data.bin"), "rb") as f:
            blob = f.read()
        for leaf in m["leaves"]:
            got = zlib.crc32(
                blob[leaf["offset"]: leaf["offset"] + leaf["nbytes"]]) \
                & 0xFFFFFFFF
            assert got == leaf["crc32"], leaf


class TestPickleBaseline:
    def test_pickle_roundtrip(self, tmp_path):
        state = _state()
        p = str(tmp_path / "s.pkl")
        n = save_pickle(p, state)
        assert n == os.path.getsize(p) > 0
        _assert_tree_equal(state, load_pickle(p))


def test_ckpt_fields_single_sourced():
    """CKPT_FIELDS names must be exactly what the bench arm emits (the
    lint_stats_names *_FIELDS scan rides the literal)."""
    from strom.ckpt.checkpoint import CKPT_FIELDS

    assert "ckpt_save_mb_per_s" in CKPT_FIELDS
    assert "ckpt_roundtrip_ok" in CKPT_FIELDS
    assert len(set(CKPT_FIELDS)) == len(CKPT_FIELDS)
