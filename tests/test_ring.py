"""Ring attention / sequence parallelism: exactness vs dense attention and
the sp train step on a dp×sp mesh (fake 8-device CPU mesh)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from strom.models.llama import LlamaConfig, attention
from strom.parallel.mesh import make_mesh
from strom.parallel.ring import make_ring_attention


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh({"sp": 8}, devices=jax.devices()[:8])


class TestRingAttention:
    @pytest.mark.parametrize("B,S,H,KV,Dh", [(2, 64, 4, 2, 16), (1, 32, 4, 4, 8)])
    def test_matches_dense(self, sp_mesh, B, S, H, KV, Dh):
        rng = np.random.default_rng(0)
        q = jnp.array(rng.normal(size=(B, S, H, Dh)), jnp.float32)
        k = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        v = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        out_ring = jax.jit(make_ring_attention(sp_mesh))(q, k, v)
        out_dense = attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                                   rtol=2e-4, atol=2e-4)

    def test_non_causal_matches(self, sp_mesh):
        rng = np.random.default_rng(1)
        q = jnp.array(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
        k = jnp.array(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
        v = jnp.array(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
        out_ring = jax.jit(make_ring_attention(sp_mesh, causal=False))(q, k, v)
        out_dense = attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                                   rtol=2e-4, atol=2e-4)

    def test_sharded_io_stays_sharded(self, sp_mesh):
        """Inputs sequence-sharded on sp → output sequence-sharded on sp."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = np.random.default_rng(2)
        sh = NamedSharding(sp_mesh, P(None, "sp", None, None))
        q = jax.device_put(rng.normal(size=(1, 64, 2, 8)).astype(np.float32), sh)
        k = jax.device_put(rng.normal(size=(1, 64, 2, 8)).astype(np.float32), sh)
        v = jax.device_put(rng.normal(size=(1, 64, 2, 8)).astype(np.float32), sh)
        out = jax.jit(make_ring_attention(sp_mesh))(q, k, v)
        assert out.sharding.spec == P(None, "sp", None, None)


class TestRingFlash:
    """Ring × Pallas flash: each ring step runs the flash kernels (interpret
    mode on the CPU mesh) and partials merge by logsumexp; backward is a
    second ring feeding the blockwise kernels the GLOBAL lse."""

    @pytest.mark.parametrize("B,S,H,KV,Dh", [(2, 64, 4, 2, 16), (1, 32, 4, 4, 8)])
    def test_forward_matches_dense(self, sp_mesh, B, S, H, KV, Dh):
        rng = np.random.default_rng(4)
        q = jnp.array(rng.normal(size=(B, S, H, Dh)), jnp.float32)
        k = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        v = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        out = jax.jit(make_ring_attention(sp_mesh, impl="flash"))(q, k, v)
        ref = attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_forward_non_causal(self, sp_mesh):
        rng = np.random.default_rng(5)
        q = jnp.array(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
        k = jnp.array(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
        v = jnp.array(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
        out = jax.jit(make_ring_attention(sp_mesh, impl="flash",
                                          causal=False))(q, k, v)
        ref = attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_dense(self, sp_mesh, causal):
        """d(sum(out * w))/d{q,k,v} must equal the dense oracle's — the ring
        backward's dk/dv travel home correctly and the global-lse blockwise
        kernels produce exact global gradients."""
        rng = np.random.default_rng(6)
        B, S, H, KV, Dh = 2, 64, 4, 2, 16
        q = jnp.array(rng.normal(size=(B, S, H, Dh)), jnp.float32)
        k = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        v = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        w = jnp.array(rng.normal(size=(B, S, H, Dh)), jnp.float32)

        ring = make_ring_attention(sp_mesh, impl="flash", causal=causal)
        g_ring = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) * w),
                                  argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(attention(q, k, v, causal=causal) * w),
            argnums=(0, 1, 2)))(q, k, v)
        for got, ref, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=5e-4, atol=5e-4,
                                       err_msg=f"d{name} mismatch")

    def test_matches_dense_ring_impl(self, sp_mesh):
        """The two ring impls are interchangeable numerically."""
        rng = np.random.default_rng(7)
        q = jnp.array(rng.normal(size=(1, 64, 4, 8)), jnp.float32)
        k = jnp.array(rng.normal(size=(1, 64, 2, 8)), jnp.float32)
        v = jnp.array(rng.normal(size=(1, 64, 2, 8)), jnp.float32)
        a = jax.jit(make_ring_attention(sp_mesh, impl="flash"))(q, k, v)
        b = jax.jit(make_ring_attention(sp_mesh, impl="dense"))(q, k, v)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)

    def test_three_axis_composition(self):
        """dp×tp×sp in one step: ring×flash over sp, tp-sharded heads,
        dp-sharded batch — loss matches the unsharded dense step."""
        from strom.parallel.train import (init_train_state, make_optimizer,
                                          make_train_step)

        cfg = LlamaConfig.tiny()
        tokens = jnp.array(
            np.random.default_rng(9).integers(0, cfg.vocab, (4, 64)), jnp.int32)
        opt = make_optimizer()
        mesh3 = make_mesh({"dp": 2, "tp": 2, "sp": 2}, devices=jax.devices()[:8])
        state3 = init_train_state(jax.random.PRNGKey(0), cfg, mesh3, opt)
        step3 = make_train_step(cfg, mesh3, opt, sp=True, attn="flash")
        _, m3 = step3(state3, tokens)

        mesh1 = make_mesh({"dp": 1}, devices=jax.devices()[:1])
        state1 = init_train_state(jax.random.PRNGKey(0), cfg, mesh1, opt)
        step1 = make_train_step(cfg, mesh1, opt, attn="dense")
        _, m1 = step1(state1, tokens)
        assert abs(float(m3["loss"]) - float(m1["loss"])) < 2e-3, \
            (float(m3["loss"]), float(m1["loss"]))

    def test_sp_flash_train_step(self):
        """make_train_step(sp=True, attn='flash') — the previously
        NotImplementedError combination — runs and matches the dense loss."""
        from strom.parallel.train import (init_train_state, make_optimizer,
                                          make_train_step)

        cfg = LlamaConfig.tiny()
        mesh = make_mesh({"dp": 2, "sp": 4}, devices=jax.devices()[:8])
        tokens = jnp.array(
            np.random.default_rng(8).integers(0, cfg.vocab, (4, 64)), jnp.int32)
        opt = make_optimizer()
        losses = {}
        for attn in ("flash", "dense"):
            state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
            step = make_train_step(cfg, mesh, opt, sp=True, attn=attn)
            state, metrics = step(state, tokens)
            losses[attn] = float(metrics["loss"])
            assert int(state.step) == 1
        assert abs(losses["flash"] - losses["dense"]) < 2e-3, losses


class TestZigzagRing:
    """Load-balanced causal ring: internal zigzag relayout (each device owns
    one early + one late half-chunk), contiguous in/out, exact parity."""

    @pytest.mark.parametrize("B,S,H,KV,Dh", [(2, 64, 4, 2, 16), (1, 32, 4, 4, 8)])
    def test_forward_matches_dense(self, sp_mesh, B, S, H, KV, Dh):
        rng = np.random.default_rng(10)
        q = jnp.array(rng.normal(size=(B, S, H, Dh)), jnp.float32)
        k = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        v = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        out = jax.jit(make_ring_attention(sp_mesh, impl="zigzag"))(q, k, v)
        ref = attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_gradients_match_dense(self, sp_mesh):
        rng = np.random.default_rng(11)
        B, S, H, KV, Dh = 2, 64, 4, 2, 16
        q = jnp.array(rng.normal(size=(B, S, H, Dh)), jnp.float32)
        k = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        v = jnp.array(rng.normal(size=(B, S, KV, Dh)), jnp.float32)
        w = jnp.array(rng.normal(size=(B, S, H, Dh)), jnp.float32)
        ring = make_ring_attention(sp_mesh, impl="zigzag")
        g_zz = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) * w),
                                argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(attention(q, k, v, causal=True) * w),
            argnums=(0, 1, 2)))(q, k, v)
        for got, ref, name in zip(g_zz, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=5e-4, atol=5e-4,
                                       err_msg=f"d{name} mismatch")

    def test_odd_ring_size(self):
        """The entry/exit permutations branch on device/chunk parity — pin
        odd n so a parity bug self-consistent for even n can't hide."""
        mesh = make_mesh({"sp": 5}, devices=jax.devices()[:5])
        rng = np.random.default_rng(14)
        q = jnp.array(rng.normal(size=(1, 40, 2, 8)), jnp.float32)
        k = jnp.array(rng.normal(size=(1, 40, 2, 8)), jnp.float32)
        v = jnp.array(rng.normal(size=(1, 40, 2, 8)), jnp.float32)
        out = jax.jit(make_ring_attention(mesh, impl="zigzag"))(q, k, v)
        ref = attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_smaller_ring_with_dp(self):
        """sp=4 alongside a dp axis; ring spans only the sp submesh."""
        mesh = make_mesh({"dp": 2, "sp": 4}, devices=jax.devices()[:8])
        rng = np.random.default_rng(12)
        q = jnp.array(rng.normal(size=(2, 32, 2, 8)), jnp.float32)
        k = jnp.array(rng.normal(size=(2, 32, 2, 8)), jnp.float32)
        v = jnp.array(rng.normal(size=(2, 32, 2, 8)), jnp.float32)
        out = jax.jit(make_ring_attention(mesh, impl="zigzag"))(q, k, v)
        ref = attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_rejects_non_causal(self, sp_mesh):
        with pytest.raises(ValueError, match="zigzag balances the CAUSAL"):
            make_ring_attention(sp_mesh, impl="zigzag", causal=False)

    def test_sp_zigzag_train_step(self):
        from strom.parallel.train import (init_train_state, make_optimizer,
                                          make_train_step)

        cfg = LlamaConfig.tiny()
        mesh = make_mesh({"dp": 2, "sp": 4}, devices=jax.devices()[:8])
        tokens = jnp.array(
            np.random.default_rng(13).integers(0, cfg.vocab, (4, 64)),
            jnp.int32)
        opt = make_optimizer()
        losses = {}
        for attn in ("zigzag", "dense"):
            state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
            step = make_train_step(cfg, mesh, opt, sp=True, attn=attn)
            state, metrics = step(state, tokens)
            losses[attn] = float(metrics["loss"])
        assert abs(losses["zigzag"] - losses["dense"]) < 2e-3, losses
        with pytest.raises(ValueError, match="needs sp=True"):
            make_train_step(cfg, mesh, opt, sp=False, attn="zigzag")


class TestSequenceParallelStep:
    def test_sp_step_matches_dense(self):
        from strom.parallel.train import (init_train_state, make_optimizer,
                                          make_train_step)

        cfg = LlamaConfig.tiny()
        mesh = make_mesh({"dp": 2, "sp": 4}, devices=jax.devices()[:8])
        tokens = jnp.array(np.random.default_rng(0).integers(0, cfg.vocab, (4, 64)),
                           jnp.int32)
        opt = make_optimizer()
        losses = {}
        for sp in (True, False):
            state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
            step = make_train_step(cfg, mesh, opt, sp=sp)
            state, metrics = step(state, tokens)
            losses[sp] = float(metrics["loss"])
            assert int(state.step) == 1
        assert abs(losses[True] - losses[False]) < 2e-3, losses

    def test_sp_pipeline_feeds_sp_step(self, tmp_path):
        """End-to-end long-context slice: seq-sharded delivery → ring step."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from strom.config import StromConfig
        from strom.delivery.core import StromContext
        from strom.parallel.train import (init_train_state, make_optimizer,
                                          make_train_step)
        from strom.pipelines import make_llama_pipeline

        cfg = LlamaConfig.tiny()
        mesh = make_mesh({"dp": 2, "sp": 4}, devices=jax.devices()[:8])
        rng = np.random.default_rng(3)
        path = str(tmp_path / "tokens.bin")
        rng.integers(0, cfg.vocab, 64 * 50, dtype=np.int32).tofile(path)
        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=8))
        try:
            opt = make_optimizer()
            state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, opt)
            step = make_train_step(cfg, mesh, opt, sp=True)
            # record length 64 = seq_len+1 divisible by sp size 4
            with make_llama_pipeline(ctx, [path], batch=4, seq_len=63,
                                     sharding=NamedSharding(mesh, P("dp", "sp"))
                                     ) as pipe:
                batch = next(pipe)
                assert batch.sharding.spec == P("dp", "sp")
                state, metrics = step(state, batch)
            assert np.isfinite(float(metrics["loss"]))
        finally:
            ctx.close()
