"""ResNet-50 and ViT-B/16 model checks (consumers of BASELINE configs #2/#3)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


class TestResNet:
    @pytest.fixture(scope="class")
    def tiny(self):
        from strom.models.resnet import ResNetConfig, init_params

        cfg = ResNetConfig.tiny()
        params, state = init_params(jax.random.PRNGKey(0), cfg)
        return cfg, params, state

    def test_forward_shapes_finite(self, tiny):
        from strom.models.resnet import forward

        cfg, params, state = tiny
        x = jnp.array(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                      dtype=jnp.float32)
        logits, new_state = forward(params, state, x, cfg, train=True)
        assert logits.shape == (2, cfg.num_classes)
        assert logits.dtype == jnp.float32
        assert bool(jnp.isfinite(logits).all())
        # bn state updated in train mode, untouched in eval mode
        assert not np.allclose(np.asarray(new_state["stem"]["mean"]),
                               np.asarray(state["stem"]["mean"]))
        _, eval_state = forward(params, state, x, cfg, train=False)
        np.testing.assert_array_equal(np.asarray(eval_state["stem"]["mean"]),
                                      np.asarray(state["stem"]["mean"]))

    @pytest.mark.slow  # convergence demo (~4s): numerics are covered
    # by the forward/grad tests above; tier-1 runtime headroom (ISSUE 5)
    def test_overfits_small_batch(self, tiny):
        import optax

        from strom.models.resnet import loss_fn

        cfg, params, state = tiny
        rng = np.random.default_rng(1)
        x = jnp.array(rng.normal(size=(8, 32, 32, 3)), dtype=jnp.float32)
        y = jnp.array(rng.integers(0, cfg.num_classes, 8), dtype=jnp.int32)
        opt = optax.adam(1e-2)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, state, opt_state):
            (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, state, x, y, cfg)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), new_state, opt_state, loss

        losses = []
        for _ in range(6):
            params, state, opt_state, loss = step(params, state, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_normalize_images(self):
        from strom.models.resnet import normalize_images

        u8 = jnp.full((1, 2, 2, 3), 128, dtype=jnp.uint8)
        out = normalize_images(u8)
        assert out.dtype == jnp.float32
        assert float(jnp.abs(out).max()) < 3.0


class TestViT:
    @pytest.fixture(scope="class")
    def tiny(self):
        from strom.models.vit import ViTConfig, init_params

        cfg = ViTConfig.tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        return cfg, params

    def test_patchify_roundtrip(self):
        from strom.models.vit import patchify

        x = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 8, 3)
        p = patchify(x, 4)
        assert p.shape == (2, 4, 48)
        # first patch == top-left 4x4 block, row-major
        np.testing.assert_array_equal(np.asarray(p[0, 0]),
                                      np.asarray(x[0, :4, :4]).reshape(-1))

    def test_forward_shapes_finite(self, tiny):
        from strom.models.vit import forward

        cfg, params = tiny
        x = jnp.array(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                      dtype=jnp.float32)
        logits = forward(params, x, cfg)
        assert logits.shape == (2, cfg.num_classes)
        assert bool(jnp.isfinite(logits).all())

    def test_permutation_equivariance_check(self, tiny):
        """Without pos embeddings ViT is patch-permutation invariant; with
        them it must NOT be — catches a dropped pos_embed wiring."""
        from strom.models.vit import forward

        cfg, params = tiny
        rng = np.random.default_rng(2)
        x = rng.normal(size=(1, 32, 32, 3)).astype(np.float32)
        x_shuf = x.copy()
        # swap two 8x8 patches
        x_shuf[0, :8, :8], x_shuf[0, :8, 8:16] = x[0, :8, 8:16], x[0, :8, :8]
        l1 = forward(params, jnp.array(x), cfg)
        l2 = forward(params, jnp.array(x_shuf), cfg)
        assert not np.allclose(np.asarray(l1), np.asarray(l2), atol=1e-5)

    @pytest.mark.slow  # convergence demo (~4s): numerics are covered
    # by the forward/permutation tests above; tier-1 runtime headroom
    def test_overfits_small_batch(self, tiny):
        import optax

        from strom.models.vit import loss_fn

        cfg, params = tiny
        rng = np.random.default_rng(3)
        x = jnp.array(rng.normal(size=(8, 32, 32, 3)), dtype=jnp.float32)
        y = jnp.array(rng.integers(0, cfg.num_classes, 8), dtype=jnp.int32)
        opt = optax.adam(1e-3)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y, cfg)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        losses = []
        for _ in range(6):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
