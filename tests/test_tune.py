"""Autotuner controller contracts (ISSUE 16) on a fake clock.

The controller is exercised via ``step()`` directly — no driver thread, no
real time. The synthetic landscape is deterministic, so every accept /
revert / hold decision here is a hard contract, not a flaky heuristic.
"""

from __future__ import annotations

import pytest

from strom.tune import TUNE_FIELDS, Autotuner, Knob, Profile


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class Landscape:
    """objective = peak - (x - opt)^2: a single-peak synthetic knob
    surface the coordinate descent must climb."""

    def __init__(self, start: float, opt: float, peak: float = 100.0):
        self.x = start
        self.opt = opt
        self.peak = peak
        self.burning = False

    def knob(self, *, lo=0.0, hi=20.0, step=1.0) -> Knob:
        return Knob(name="x", get=lambda: self.x,
                    set=lambda v: setattr(self, "x", v),
                    lo=lo, hi=hi, step=step,
                    quantize=lambda v: float(round(v)), min_step=1.0)

    def metrics(self) -> dict:
        return {"objective": self.peak - (self.x - self.opt) ** 2,
                "slo_burning": self.burning}


def _mk(land: Landscape, **kw) -> tuple[Autotuner, FakeClock]:
    clock = FakeClock()
    tuner = Autotuner([land.knob()], land.metrics, clock=clock, **kw)
    return tuner, clock


def drive(tuner: Autotuner, clock: FakeClock, steps: int) -> list[str]:
    out = []
    for _ in range(steps):
        out.append(tuner.step())
        clock.advance(1.0)
    return out


class TestConvergence:
    def test_climbs_to_the_optimum(self):
        land = Landscape(start=2.0, opt=9.0)
        tuner, clock = _mk(land)
        drive(tuner, clock, 60)
        # coordinate descent with integer quantization must land within
        # one quantum of the peak and stay there
        assert abs(land.x - land.opt) <= 1.0
        s = tuner.stats()
        assert s["tune_moves"] >= 5          # it actually walked there
        assert s["tuned_vs_baseline"] >= 1.0

    def test_tuned_never_below_baseline(self):
        """The safety contract tuned_vs_hand rides on: only measured-better
        moves persist, so the objective at ANY settled point is >= the
        first measurement."""
        land = Landscape(start=15.0, opt=5.0)
        tuner, clock = _mk(land)
        baseline = land.metrics()["objective"]
        for _ in range(80):
            tuner.step()
            clock.advance(1.0)
            if tuner._pending is None:  # settled state only
                assert land.metrics()["objective"] >= baseline - 1e-9

    def test_converges_from_above(self):
        land = Landscape(start=18.0, opt=6.0)
        tuner, clock = _mk(land)
        drive(tuner, clock, 80)
        assert abs(land.x - land.opt) <= 1.0


class TestGuardedStep:
    def test_regression_is_reverted(self):
        """A trial that measures worse is undone exactly."""
        land = Landscape(start=9.0, opt=9.0)  # already at the peak
        tuner, clock = _mk(land)
        assert tuner.step() == "propose"      # first beat measures+proposes
        moved = land.x
        assert moved != 9.0
        assert tuner.step() == "revert"       # any move off the peak loses
        assert land.x == 9.0
        assert tuner.stats()["tune_reverts"] == 1

    def test_hard_regression_halves_the_step(self):
        land = Landscape(start=9.0, opt=9.0)
        clock = FakeClock()
        # coarse step: moving 2 off the peak costs 4 points > guard band
        tuner = Autotuner([land.knob(step=2.0)], land.metrics,
                          clock=clock, guard_frac=0.01)
        tuner.step()
        tuner.step()  # revert past the guard band
        assert tuner._step["x"] == 1.0

    def test_both_directions_worse_advances_the_cursor(self):
        land = Landscape(start=9.0, opt=9.0)
        tuner, clock = _mk(land)
        start_i = tuner._knob_i
        drive(tuner, clock, 6)  # two full failed trials in both directions
        assert tuner._knob_i > start_i
        assert land.x == 9.0


class TestSloHold:
    def test_never_tunes_while_burning(self):
        land = Landscape(start=2.0, opt=9.0)
        land.burning = True
        tuner, clock = _mk(land)
        results = drive(tuner, clock, 10)
        assert set(results) == {"hold"}
        assert land.x == 2.0                  # not one knob moved
        assert tuner.stats()["tune_holds"] == 10
        assert tuner.stats()["tune_trials"] == 0

    def test_inflight_trial_reverted_on_burn(self):
        land = Landscape(start=2.0, opt=9.0)
        tuner, clock = _mk(land)
        assert tuner.step() == "propose"
        assert land.x != 2.0
        land.burning = True
        assert tuner.step() == "hold"         # the trial is rolled back
        assert land.x == 2.0
        land.burning = False
        assert tuner.step() == "propose"      # resumes when clean


class TestProfiles:
    def test_save_load_round_trip(self, tmp_path):
        land = Landscape(start=2.0, opt=9.0)
        tuner, clock = _mk(land, profile_name="resnet")
        drive(tuner, clock, 40)
        p = tuner.profile()
        path = str(tmp_path / "resnet.json")
        p.save(path)
        q = Profile.load(path)
        assert q.name == "resnet"
        assert q.knobs == p.knobs
        assert q.objective == pytest.approx(p.objective)

    def test_apply_profile_sets_and_clamps(self):
        land = Landscape(start=2.0, opt=9.0)
        tuner, _ = _mk(land)
        n = tuner.apply_profile(Profile(name="p", knobs={"x": 500.0,
                                                         "ghost": 3.0}))
        assert n == 1                          # unknown names are ignored
        assert land.x == 20.0                  # clamped to the knob's hi

    def test_saved_profile_restarts_at_the_converged_point(self, tmp_path):
        land = Landscape(start=2.0, opt=9.0)
        tuner, clock = _mk(land)
        drive(tuner, clock, 60)
        path = str(tmp_path / "p.json")
        tuner.profile().save(path)
        fresh = Landscape(start=2.0, opt=9.0)
        t2, _ = _mk(fresh)
        t2.apply_profile(Profile.load(path))
        assert abs(fresh.x - land.x) < 1e-9


class TestStatsSurface:
    def test_every_tune_field_present_and_numeric(self):
        land = Landscape(start=2.0, opt=9.0)
        tuner, clock = _mk(land)
        drive(tuner, clock, 8)
        s = tuner.stats()
        for k in TUNE_FIELDS:
            assert k in s, f"missing {k}"
            assert isinstance(s[k], (int, float)), k
        assert isinstance(s["tune_profile"], str)
        assert isinstance(s["tune_last_move"], str)
        assert "x" in s["tune_knobs"]

    def test_driver_thread_lifecycle(self):
        land = Landscape(start=2.0, opt=9.0)
        tuner = Autotuner([land.knob()], land.metrics, interval_s=0.01)
        tuner.start()
        try:
            import time as _t

            deadline = _t.monotonic() + 5.0
            while tuner.stats()["tune_trials"] < 2:
                assert _t.monotonic() < deadline, "tuner thread never ran"
                _t.sleep(0.01)
            assert tuner.stats()["tune_active"] == 1
        finally:
            tuner.close()
        assert tuner.stats()["tune_active"] == 0
