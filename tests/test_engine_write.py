"""Engine-API conformance for the WRITE path (ISSUE 13 tentpole):
``submit_vectored(op="write")`` / ``write_vectored`` semantics over EVERY
Engine implementation — the python thread-pool engine, the native io_uring
engine, and the multi-ring engine in both shapes (tests/test_engine_api.py
pattern). One behavioral contract, three machines: exactly-once completion
accounting, read-back bit-identity, short-write retry, cancel/close with a
live write token, fan-out index mapping."""

import errno
import os
import threading

import numpy as np
import pytest

from strom.config import StromConfig
from strom.engine.base import EngineError

MiB = 1024 * 1024


def _uring_ok() -> bool:
    from strom.engine.uring_engine import uring_available

    return uring_available()


@pytest.fixture(params=["python", "uring", "multi", "multi2"])
def any_engine(request):
    cfg = StromConfig(queue_depth=8, num_buffers=16)
    if request.param == "python":
        from strom.engine.python_engine import PythonEngine

        eng = PythonEngine(cfg)
    elif request.param == "uring":
        if not _uring_ok():
            pytest.skip("io_uring unavailable in this sandbox")
        from strom.engine.uring_engine import UringEngine

        eng = UringEngine(cfg)
    else:
        if not _uring_ok():
            pytest.skip("io_uring unavailable in this sandbox")
        from strom.engine.multi import MultiRingEngine

        eng = MultiRingEngine(cfg, rings=2 if request.param == "multi2" else 1)
    yield eng
    eng.close()


def _mk_file(path, nbytes: int = 0) -> str:
    fd = os.open(str(path), os.O_WRONLY | os.O_CREAT, 0o644)
    try:
        if nbytes:
            os.ftruncate(fd, nbytes)
    finally:
        os.close(fd)
    return str(path)


class TestWriteVectored:
    def test_integrity_and_exactly_once_accounting(self, any_engine,
                                                   tmp_path, rng):
        """Every write chunk completes exactly once; the bytes land where
        the plan says (read back bit-identical via plain file read)."""
        p = _mk_file(tmp_path / "w.bin")
        fi = any_engine.register_file(p, writable=True)
        data = rng.integers(0, 256, 4 * MiB, dtype=np.uint8)
        per = len(data) // 16
        chunks = [(fi, i * per, i * per, per) for i in range(16)]
        tok = any_engine.submit_vectored(chunks, data, op="write")
        seen = []
        while not tok.done:
            for c in any_engine.poll(tok, min_completions=1):
                assert c.result == per
                seen.append(c.index)
        assert sorted(seen) == list(range(16))
        assert any_engine.drain(tok) == len(data)
        assert any_engine.in_flight() == 0
        np.testing.assert_array_equal(np.fromfile(p, dtype=np.uint8), data)

    def test_blocking_write_vectored_and_readback_via_engine(
            self, any_engine, tmp_path, rng):
        """write_vectored then read_vectored through the SAME engine:
        bit-identity across the full O_DIRECT round trip."""
        p = _mk_file(tmp_path / "rt.bin")
        fi = any_engine.register_file(p, writable=True)
        data = rng.integers(0, 256, 2 * MiB, dtype=np.uint8)
        assert any_engine.write_vectored([(fi, 0, 0, len(data))],
                                         data) == len(data)
        dest = np.zeros(len(data), dtype=np.uint8)
        assert any_engine.read_vectored([(fi, 0, 0, len(data))],
                                        dest) == len(data)
        np.testing.assert_array_equal(dest, data)

    def test_unaligned_offset_falls_back_buffered(self, any_engine,
                                                  tmp_path, rng):
        p = _mk_file(tmp_path / "u.bin", 4096)
        fi = any_engine.register_file(p, writable=True)
        data = rng.integers(0, 256, 1000, dtype=np.uint8)
        assert any_engine.write_vectored([(fi, 7, 0, 1000)], data) == 1000
        back = np.fromfile(p, dtype=np.uint8)
        np.testing.assert_array_equal(back[7:1007], data)

    def test_multi_piece_chunks_complete_once(self, any_engine, tmp_path,
                                              rng):
        """A chunk larger than block_size (several engine ops) surfaces as
        ONE completion, on its last piece."""
        p = _mk_file(tmp_path / "mp.bin")
        fi = any_engine.register_file(p, writable=True)
        ln = 1 * MiB  # 8 block-size pieces at the 128KiB default
        data = rng.integers(0, 256, 2 * ln, dtype=np.uint8)
        chunks = [(fi, 0, 0, ln), (fi, ln, ln, ln)]
        tok = any_engine.submit_vectored(chunks, data, op="write")
        seen = []
        while not tok.done:
            seen.extend(any_engine.poll(tok, min_completions=1))
        assert sorted(c.index for c in seen) == [0, 1]
        assert all(c.result == ln for c in seen)
        assert any_engine.drain(tok) == 2 * ln
        np.testing.assert_array_equal(np.fromfile(p, dtype=np.uint8), data)

    def test_sequential_write_read_cycles(self, any_engine, tmp_path, rng):
        """Alternating writes and reads leave the engine clean (no stale
        tags, no leaked depth) — and in-place rewrites win."""
        p = _mk_file(tmp_path / "cyc.bin")
        fi = any_engine.register_file(p, writable=True)
        for round_i in range(3):
            data = rng.integers(0, 256, 1 * MiB, dtype=np.uint8)
            assert any_engine.write_vectored([(fi, 0, 0, len(data))],
                                             data) == len(data)
            dest = np.zeros(len(data), dtype=np.uint8)
            any_engine.read_vectored([(fi, 0, 0, len(data))], dest)
            np.testing.assert_array_equal(dest, data)
        assert any_engine.in_flight() == 0

    def test_write_to_readonly_registration_fails(self, any_engine,
                                                  tmp_path, rng):
        """A write against a read-only registration fails loudly (EBADF or
        EINVAL per engine) instead of corrupting anything silently."""
        p = _mk_file(tmp_path / "ro.bin", 1 * MiB)
        fi = any_engine.register_file(p)  # NOT writable
        data = rng.integers(0, 256, 64 * 1024, dtype=np.uint8)
        with pytest.raises(EngineError):
            any_engine.write_vectored([(fi, 0, 0, len(data))], data,
                                      retries=0)

    def test_cancel_reaps_everything(self, any_engine, tmp_path, rng):
        p = _mk_file(tmp_path / "c.bin")
        fi = any_engine.register_file(p, writable=True)
        data = rng.integers(0, 256, 4 * MiB, dtype=np.uint8)
        per = len(data) // 16
        chunks = [(fi, i * per, i * per, per) for i in range(16)]
        tok = any_engine.submit_vectored(chunks, data, op="write")
        any_engine.cancel(tok)
        assert tok.cancelled
        assert any_engine.in_flight() == 0
        with pytest.raises(EngineError):
            any_engine.poll(tok)

    def test_close_cancels_live_write_token(self, any_engine, tmp_path,
                                            rng):
        p = _mk_file(tmp_path / "cl.bin")
        fi = any_engine.register_file(p, writable=True)
        data = rng.integers(0, 256, 4 * MiB, dtype=np.uint8)
        per = len(data) // 16
        chunks = [(fi, i * per, i * per, per) for i in range(16)]
        tok = any_engine.submit_vectored(chunks, data, op="write")
        t = threading.Thread(target=any_engine.close)
        t.start()
        t.join(timeout=30)
        assert not t.is_alive(), "close() hung on a live write token"
        assert tok.cancelled


@pytest.fixture()
def py_multi(monkeypatch):
    """2-ring MultiRingEngine over PYTHON children (fan-out state machine
    without io_uring — tests/test_engine_api.py pattern)."""
    import strom.engine.multi as multi_mod  # noqa: F401
    import strom.engine.uring_engine as ue
    from strom.engine.python_engine import PythonEngine

    class _PyChild(PythonEngine):
        def __init__(self, config, variant=""):
            super().__init__(config)

    monkeypatch.setattr(ue, "UringEngine", _PyChild)
    from strom.engine.multi import MultiRingEngine

    eng = MultiRingEngine(StromConfig(queue_depth=8, num_buffers=16),
                          rings=2)
    yield eng
    eng.close()


class TestFanOutWrites:
    def test_two_file_write_fanout_integrity(self, py_multi, tmp_path, rng):
        """A two-file write gather fans per ring; completions map back to
        the CALLER's chunk indices and each file lands its own bytes."""
        paths = [_mk_file(tmp_path / f"f{i}.bin") for i in range(2)]
        fis = [py_multi.register_file(p, writable=True) for p in paths]
        half = 512 * 1024
        src = rng.integers(0, 256, 4 * half, dtype=np.uint8)
        chunks = [(fis[0], 0, 0, half), (fis[1], 0, half, half),
                  (fis[0], half, 2 * half, half),
                  (fis[1], half, 3 * half, half)]
        tok = py_multi.submit_vectored(chunks, src, op="write")
        seen = []
        while not tok.done:
            seen.extend(py_multi.poll(tok, min_completions=1))
        assert sorted(c.index for c in seen) == [0, 1, 2, 3]
        assert py_multi.drain(tok) == 4 * half
        f0 = np.fromfile(paths[0], dtype=np.uint8)
        f1 = np.fromfile(paths[1], dtype=np.uint8)
        np.testing.assert_array_equal(f0[:half], src[:half])
        np.testing.assert_array_equal(f0[half:], src[2 * half: 3 * half])
        np.testing.assert_array_equal(f1[:half], src[half: 2 * half])
        np.testing.assert_array_equal(f1[half:], src[3 * half:])


class TestWriteFaults:
    def _faulty(self, rules, seed=0):
        from strom.faults import FaultPlan, FaultyEngine
        from strom.faults.plan import FaultRule
        from strom.engine.python_engine import PythonEngine

        plan = FaultPlan([FaultRule(**r) for r in rules], seed=seed)
        return FaultyEngine(PythonEngine(
            StromConfig(queue_depth=8, num_buffers=16)), plan), plan

    def test_short_write_retried_to_full_bytes(self, tmp_path, rng):
        """An injected short write is retried (whole-piece rewrite, the
        read path's contract) and the full bytes land bit-identical."""
        eng, plan = self._faulty([
            {"kind": "short_read", "op": "write", "times": 2,
             "short_frac": 0.5}])
        try:
            p = _mk_file(tmp_path / "sw.bin")
            fi = eng.register_file(p, writable=True)
            data = rng.integers(0, 256, 1 * MiB, dtype=np.uint8)
            assert eng.write_vectored([(fi, 0, 0, len(data))], data,
                                      retries=2) == len(data)
            np.testing.assert_array_equal(np.fromfile(p, dtype=np.uint8),
                                          data)
            assert plan.stats()["faults_injected"] >= 1
        finally:
            eng.close()

    def test_transient_errno_write_retried(self, tmp_path, rng):
        eng, plan = self._faulty([
            {"kind": "errno", "op": "write", "times": 1,
             "err": errno.EIO}])
        try:
            p = _mk_file(tmp_path / "ew.bin")
            fi = eng.register_file(p, writable=True)
            data = rng.integers(0, 256, 512 * 1024, dtype=np.uint8)
            assert eng.write_vectored([(fi, 0, 0, len(data))], data,
                                      retries=2) == len(data)
            np.testing.assert_array_equal(np.fromfile(p, dtype=np.uint8),
                                          data)
        finally:
            eng.close()

    def test_read_rule_never_fires_on_writes(self, tmp_path, rng):
        """An op='read' rule (the chaos preset's shape) must not inject
        into — or consume RNG draws for — write traffic."""
        eng, plan = self._faulty([
            {"kind": "errno", "op": "read", "p": 1.0}])
        try:
            p = _mk_file(tmp_path / "nr.bin")
            fi = eng.register_file(p, writable=True)
            data = rng.integers(0, 256, 256 * 1024, dtype=np.uint8)
            assert eng.write_vectored([(fi, 0, 0, len(data))], data,
                                      retries=0) == len(data)
            assert plan.stats()["faults_injected"] == 0
        finally:
            eng.close()


class TestSchedulerWrites:
    def test_write_chunks_grants_and_bit_identity(self, tmp_path, rng):
        """Scheduler-granted writes (PR 7 budgets/priority apply): sliced
        grants, bytes identical, tenant accounting lands."""
        from strom.delivery.core import StromContext

        ctx = StromContext(StromConfig(engine="python", queue_depth=8,
                                       num_buffers=16,
                                       slab_pool_bytes=32 * MiB))
        try:
            assert ctx.scheduler is not None
            p = _mk_file(tmp_path / "sch.bin")
            data = rng.integers(0, 256, 2 * MiB, dtype=np.uint8)
            t = ctx.register_tenant("writer")
            ctx.pwrite(p, data, tenant="writer", fsync=True)
            back = ctx.pread(p)
            np.testing.assert_array_equal(back[: len(data)], data)
            assert t.granted_bytes >= len(data)
        finally:
            ctx.close()
